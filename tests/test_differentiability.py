"""Differentiability (jax.grad) and half-precision (bf16) test tiers.

Reference: ``tests/unittests/helpers/testers.py:443-543`` asserts ``is_differentiable``
against autograd and runs fp16 passes; here ``jax.grad`` finiteness/non-zeroness and a
bf16-vs-f32 relaxed-tolerance pass cover the tensor-native families.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from tests.testers import MetricTester

from torchmetrics_tpu import regression
from torchmetrics_tpu.functional.audio.snr import scale_invariant_signal_noise_ratio, signal_noise_ratio
from torchmetrics_tpu.functional.classification.calibration_error import binary_calibration_error
from torchmetrics_tpu.functional.classification.hinge import multiclass_hinge_loss
from torchmetrics_tpu.functional.image.psnr import peak_signal_noise_ratio
from torchmetrics_tpu.functional.image.ssim import structural_similarity_index_measure
from torchmetrics_tpu.functional.regression.concordance import concordance_corrcoef
from torchmetrics_tpu.functional.regression.cosine_similarity import cosine_similarity
from torchmetrics_tpu.functional.regression.explained_variance import explained_variance
from torchmetrics_tpu.functional.regression.mae import mean_absolute_error
from torchmetrics_tpu.functional.regression.mse import mean_squared_error
from torchmetrics_tpu.functional.regression.pearson import pearson_corrcoef
from torchmetrics_tpu.functional.regression.r2 import r2_score
from torchmetrics_tpu.functional.text.perplexity import perplexity

_RNG = np.random.default_rng(11)
_N = 64

_p_reg = _RNG.normal(size=_N).astype(np.float32)
_t_reg = (0.7 * _p_reg + 0.4 * _RNG.normal(size=_N)).astype(np.float32)
_p_prob = _RNG.uniform(0.05, 0.95, size=_N).astype(np.float32)
_t_bin = _RNG.integers(0, 2, size=_N)
_logits = _RNG.normal(size=(_N, 5)).astype(np.float32)
_t_mc = _RNG.integers(0, 5, size=_N)
_audio_p = _RNG.normal(size=(4, 256)).astype(np.float32)
_audio_t = (_audio_p + 0.2 * _RNG.normal(size=(4, 256))).astype(np.float32)
_img_a = _RNG.uniform(0, 1, size=(2, 3, 32, 32)).astype(np.float32)
_img_b = np.clip(_img_a + 0.05 * _RNG.normal(size=_img_a.shape), 0, 1).astype(np.float32)
_lm_logits = _RNG.normal(size=(2, 16, 30)).astype(np.float32)
_lm_target = _RNG.integers(0, 30, size=(2, 16))

# (id, functional, preds, target, kwargs, modular class or None)
_DIFFERENTIABLE_CASES = [
    ("mse", mean_squared_error, _p_reg, _t_reg, {}, regression.MeanSquaredError),
    ("mae", mean_absolute_error, _p_reg, _t_reg, {}, regression.MeanAbsoluteError),
    ("pearson", pearson_corrcoef, _p_reg, _t_reg, {}, regression.PearsonCorrCoef),
    ("concordance", concordance_corrcoef, _p_reg, _t_reg, {}, regression.ConcordanceCorrCoef),
    ("r2", r2_score, _p_reg, _t_reg, {}, regression.R2Score),
    ("explained_variance", explained_variance, _p_reg, _t_reg, {}, regression.ExplainedVariance),
    ("cosine", cosine_similarity, _p_reg.reshape(8, 8), _t_reg.reshape(8, 8), {}, regression.CosineSimilarity),
    ("hinge", multiclass_hinge_loss, _logits, _t_mc, {"num_classes": 5}, None),
    ("calibration", binary_calibration_error, _p_prob, _t_bin, {"n_bins": 10}, None),
    ("snr", signal_noise_ratio, _audio_p, _audio_t, {}, None),
    ("si_snr", scale_invariant_signal_noise_ratio, _audio_p, _audio_t, {}, None),
    ("psnr", peak_signal_noise_ratio, _img_a, _img_b, {"data_range": 1.0}, None),
    ("ssim", structural_similarity_index_measure, _img_a, _img_b, {"data_range": 1.0}, None),
    ("perplexity", perplexity, _lm_logits, _lm_target, {}, None),
]


class TestDifferentiability(MetricTester):
    @pytest.mark.parametrize(
        ("fn", "preds", "target", "kwargs", "cls"),
        [c[1:] for c in _DIFFERENTIABLE_CASES],
        ids=[c[0] for c in _DIFFERENTIABLE_CASES],
    )
    def test_grad_finite_and_nonzero(self, fn, preds, target, kwargs, cls):
        self.run_differentiability_test(preds, target, fn, metric_class=cls, metric_args=kwargs)


_BF16_CASES = [
    ("mse", mean_squared_error, _p_reg, _t_reg, {}, 1e-2),
    ("mae", mean_absolute_error, _p_reg, _t_reg, {}, 1e-2),
    ("pearson", pearson_corrcoef, _p_reg, _t_reg, {}, 2e-2),
    ("r2", r2_score, _p_reg, _t_reg, {}, 5e-2),
    ("cosine", cosine_similarity, _p_reg.reshape(8, 8), _t_reg.reshape(8, 8), {}, 1e-2),
    ("hinge", multiclass_hinge_loss, _logits, _t_mc, {"num_classes": 5}, 2e-2),
    ("snr", signal_noise_ratio, _audio_p, _audio_t, {}, 2e-1),
    ("psnr", peak_signal_noise_ratio, _img_a, _img_b, {"data_range": 1.0}, 5e-1),
    ("ssim", structural_similarity_index_measure, _img_a, _img_b, {"data_range": 1.0}, 5e-2),
    ("perplexity", perplexity, _lm_logits, _lm_target, {}, 5e-1),
]


class TestBF16Precision(MetricTester):
    @pytest.mark.parametrize(
        ("fn", "preds", "target", "kwargs", "atol"),
        [c[1:] for c in _BF16_CASES],
        ids=[c[0] for c in _BF16_CASES],
    )
    def test_bf16_matches_f32(self, fn, preds, target, kwargs, atol):
        self.run_precision_test(preds, target, fn, metric_args=kwargs, atol=atol, rtol=5e-2)

    def test_bf16_stat_scores_exact(self):
        """Label-based classification counters are integer work — bf16 probs in,
        exact counts out."""
        from torchmetrics_tpu.functional.classification.accuracy import multiclass_accuracy

        ref = multiclass_accuracy(jnp.asarray(_logits), jnp.asarray(_t_mc), num_classes=5)
        low = multiclass_accuracy(jnp.asarray(_logits, jnp.bfloat16), jnp.asarray(_t_mc), num_classes=5)
        np.testing.assert_allclose(float(low), float(ref), atol=1e-6)
