"""Fault-tolerance layer tests (parallel/resilience.py, parallel/faults.py,
engine/epoch.py degraded mode): bounded collectives with typed classification,
retry/backoff recovery, degraded-mode folding over surviving membership, payload
CRC integrity, the eager-path deadline, and the deterministic injection harness."""

import os
import time
from unittest import mock

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from torchmetrics_tpu import MetricCollection
from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision
from torchmetrics_tpu.diag import diag_context
from torchmetrics_tpu.engine import engine_context
from torchmetrics_tpu.parallel import (
    CollectiveTimeout,
    CollectiveTimeoutError,
    CorruptPayload,
    DelayRank,
    RankDrop,
    RankUnreachableError,
    fault_context,
    gather_all_tensors,
    resilience_context,
)
from torchmetrics_tpu.parallel import faults as faults_mod
from torchmetrics_tpu.parallel import resilience as res_mod

NUM_CLASSES = 5


def _identical_rank_world(monkeypatch, world=2):
    """Every rank holds this process's state: allgather = stack world copies."""
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: world)
    monkeypatch.setattr(
        multihost_utils, "process_allgather", lambda x, tiled=False: np.stack([np.asarray(x)] * world)
    )


def _metric(**kw):
    m = MulticlassAccuracy(NUM_CLASSES, average="macro", validate_args=False, **kw)
    m.distributed_available_fn = lambda: True
    return m


def _batch(seed=0, n=16):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.rand(n, NUM_CLASSES).astype(np.float32)),
        jnp.asarray(rng.randint(0, NUM_CLASSES, n)),
    )


# ------------------------------------------------------------------ harness


def test_fault_due_is_deterministic_and_bounded():
    f = CollectiveTimeout(label="reduce:*", times=2, after=1)
    assert not f.due("meta", None)  # label mismatch consumes nothing
    assert not f.due("reduce:int32", None)  # skipped by `after`
    assert f.due("reduce:int32", None)
    assert f.due("reduce:float32", None)
    assert not f.due("reduce:int32", None)  # times exhausted
    assert f.fired == 2


def test_rank_scoped_fault_respects_membership():
    f = RankDrop(rank=1)
    assert f.due("reduce:int32", (0, 1))
    # the degraded re-plan removed rank 1: the fault neither fires nor counts
    assert not f.due("reduce:int32", (0,))
    assert f.due("reduce:int32", None)  # unknown membership = full world


def test_rank_scoped_fault_requires_rank():
    with pytest.raises(ValueError, match="requires a target rank"):
        DelayRank(rank=None, delay_ms=5)  # type: ignore[arg-type]


def test_fault_context_scopes_and_restores():
    assert faults_mod.active_faults() == ()
    with fault_context(CollectiveTimeout()) as planted:
        assert faults_mod.active_faults() == planted
    assert faults_mod.active_faults() == ()


# ------------------------------------------------------------------ policy


def test_policy_env_parsing(monkeypatch):
    monkeypatch.setenv(res_mod.DEADLINE_ENV_VAR, "1500")
    monkeypatch.setenv(res_mod.RETRIES_ENV_VAR, "5")
    monkeypatch.setenv(res_mod.BACKOFF_ENV_VAR, "10")
    monkeypatch.setenv(res_mod.DEGRADED_ENV_VAR, "0")
    policy = res_mod.current_policy()
    assert policy.deadline_ms == 1500.0
    assert policy.retries == 5
    assert policy.backoff_ms == 10.0
    assert policy.degraded is False


def test_policy_defaults_add_no_deadline(monkeypatch):
    for var in (res_mod.DEADLINE_ENV_VAR, res_mod.RETRIES_ENV_VAR, res_mod.BACKOFF_ENV_VAR):
        monkeypatch.delenv(var, raising=False)
    policy = res_mod.current_policy()
    assert policy.deadline_ms is None  # unbounded = zero extra machinery
    assert policy.degraded is True


def test_resilience_context_overrides_env(monkeypatch):
    monkeypatch.setenv(res_mod.DEADLINE_ENV_VAR, "1500")
    with resilience_context(deadline_ms=50, retries=0) as policy:
        assert res_mod.current_policy() is policy
        assert policy.deadline_ms == 50.0
    assert res_mod.current_policy().deadline_ms == 1500.0


# ------------------------------------------------------------------ bounded collectives


def test_deadline_escapes_hanging_collective(monkeypatch):
    """A genuinely hanging collective returns a typed timeout, not a hang."""
    _identical_rank_world(monkeypatch)
    from jax.experimental import multihost_utils

    def hanging(x, tiled=False):
        time.sleep(5.0)
        return np.stack([np.asarray(x)] * 2)

    monkeypatch.setattr(multihost_utils, "process_allgather", hanging)
    t0 = time.perf_counter()
    with resilience_context(deadline_ms=100, retries=0):
        with pytest.raises(CollectiveTimeoutError) as err:
            gather_all_tensors(jnp.ones((3,)))
    assert time.perf_counter() - t0 < 2.0  # escaped well before the 5 s hang
    assert err.value.label == "eager:shape"  # the ragged path's FIRST collective


def test_eager_path_timeout_is_typed_and_retryable(monkeypatch):
    """The eager gather (the EngineStats.fallback path) recovers by retry."""
    _identical_rank_world(monkeypatch)
    with resilience_context(retries=1, backoff_ms=1), fault_context(
        CollectiveTimeout(label="eager:state", times=1)
    ):
        out = gather_all_tensors(jnp.arange(4.0))
    assert len(out) == 2
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(4.0))


def test_retry_exhaustion_raises_typed_error(monkeypatch):
    _identical_rank_world(monkeypatch)
    with resilience_context(retries=1, backoff_ms=1), fault_context(
        CollectiveTimeout(times=None)  # every attempt times out
    ):
        with pytest.raises(CollectiveTimeoutError) as err:
            gather_all_tensors(jnp.arange(4.0))
    assert err.value.attempts == 2  # initial + 1 bounded retry


def test_rank_drop_is_not_retryable(monkeypatch):
    """A dead rank fails immediately — retrying cannot resurrect it."""
    _identical_rank_world(monkeypatch)
    calls = {"n": 0}
    from jax.experimental import multihost_utils

    orig = multihost_utils.process_allgather

    def counting(x, tiled=False):
        calls["n"] += 1
        return np.stack([np.asarray(x)] * 2)

    monkeypatch.setattr(multihost_utils, "process_allgather", counting)
    with resilience_context(retries=5, backoff_ms=1), fault_context(RankDrop(rank=1)):
        with pytest.raises(RankUnreachableError) as err:
            gather_all_tensors(jnp.arange(4.0))
    assert err.value.rank == 1
    assert calls["n"] == 0  # failed before ever entering the collective again


def test_corrupt_payload_detected_and_retried(monkeypatch):
    """Bit-flipped local row fails the CRC echo check; the retry recovers."""
    _identical_rank_world(monkeypatch)
    with diag_context() as rec, resilience_context(
        retries=2, backoff_ms=1, verify_payload=True
    ), fault_context(CorruptPayload(rank=0, times=1)):
        out = gather_all_tensors(jnp.arange(4.0))
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(4.0))
    assert rec.counts.get("sync.retry", 0) == 1


# ------------------------------------------------------------------ packed path


def test_packed_sync_timeout_recovers_with_parity(monkeypatch):
    _identical_rank_world(monkeypatch)
    preds, target = _batch()

    eager = _metric(compiled_update=False)
    eager.update(preds, target)
    want = float(eager.compute())

    with engine_context(True), resilience_context(retries=2, backoff_ms=1), fault_context(
        CollectiveTimeout(times=1)
    ), diag_context() as rec:
        m = _metric()
        m.update(preds, target)
        got = float(m.compute())
    st = m._epoch.stats
    assert got == want
    assert st.sync_retries == 1
    assert st.sync_degraded_folds == 0
    assert rec.counts.get("sync.retry", 0) == 1


def test_packed_sync_rank_drop_degrades_excluding_correct_rank(monkeypatch):
    _identical_rank_world(monkeypatch)
    preds, target = _batch(1)

    local = _metric(compiled_update=False)
    local.update(preds, target)
    want_local = float(local.compute())  # survivor fold of the identical world

    with engine_context(True), resilience_context(retries=0, backoff_ms=1), fault_context(
        RankDrop(rank=1)
    ), diag_context() as rec:
        m = _metric()
        m.update(preds, target)
        got = float(m.compute())
    st = m._epoch.stats
    assert got == want_local
    assert st.sync_degraded_folds == 1
    degraded = [e for e in rec.snapshot() if e.kind == "sync.degraded"]
    assert degraded and degraded[-1].data["rank"] == 1
    assert degraded[-1].data["survivors"] == (0,)


def test_degraded_disallowed_raises_instead(monkeypatch):
    _identical_rank_world(monkeypatch)
    preds, target = _batch(2)
    with engine_context(True), resilience_context(retries=0, degraded=False), fault_context(
        RankDrop(rank=1)
    ):
        m = _metric()
        m.update(preds, target)
        with pytest.raises(RankUnreachableError):
            m.compute()


def test_delayed_rank_past_deadline_names_the_rank(monkeypatch):
    """DelayRank genuinely sleeps; past the deadline the timeout carries the
    culprit rank, so the degraded fold excludes exactly it."""
    _identical_rank_world(monkeypatch)
    preds, target = _batch(3)
    with engine_context(True), resilience_context(
        deadline_ms=10, retries=0, backoff_ms=1
    ), fault_context(DelayRank(rank=1, delay_ms=30, times=None)), diag_context() as rec:
        m = _metric()
        m.update(preds, target)
        m.compute()
    st = m._epoch.stats
    assert st.sync_degraded_folds == 1
    degraded = [e for e in rec.snapshot() if e.kind == "sync.degraded"]
    assert degraded[-1].data["rank"] == 1
    assert degraded[-1].data["error"] == "CollectiveTimeoutError"


def test_degraded_plan_is_membership_keyed(monkeypatch):
    """A degraded fold never reuses the full-world executable: the plan
    signature carries the membership, so the caches stay disjoint."""
    _identical_rank_world(monkeypatch)
    preds, target = _batch(4)
    with engine_context(True), resilience_context(retries=0, backoff_ms=1):
        m = _metric()
        m.update(preds, target)
        m.compute()  # clean full-world sync compiles the (0, 1) fold
        traces_full = m._epoch.stats.sync_fold_traces
        m.reset()
        m.update(preds, target)
        with fault_context(RankDrop(rank=1)):
            m.compute()  # degraded (0,)-fold must compile separately
        assert m._epoch.stats.sync_fold_traces == traces_full + 1
        assert m._epoch.stats.sync_degraded_folds == 1


def test_collection_packed_sync_degrades_once_for_all_owners(monkeypatch):
    _identical_rank_world(monkeypatch)
    preds, target = _batch(5)
    from torchmetrics_tpu.classification import MulticlassConfusionMatrix

    # two DISTINCT compute groups (different state layouts) so the sync rides
    # the collection-wide CollectionEpoch plan, not a single owner's engine
    build = lambda: {
        "acc": MulticlassAccuracy(NUM_CLASSES, average="macro", validate_args=False),
        "cm": MulticlassConfusionMatrix(NUM_CLASSES, validate_args=False),
    }
    # the survivor fold over the identical-rank world {0} equals the plain
    # LOCAL compute (no sync) — NOT the full-world fold, which doubles counts
    local_mc = MetricCollection(build(), compute_groups=False, fused_dispatch=False)
    for m in local_mc._modules.values():
        m.compiled_update = False
        m.distributed_available_fn = lambda: False  # the emulated world is world-2
    local_mc.update(preds, target)
    want = {k: np.asarray(v) for k, v in local_mc.compute().items()}

    with engine_context(True), resilience_context(retries=0, backoff_ms=1), fault_context(
        RankDrop(rank=1)
    ):
        mc = MetricCollection(build(), compute_groups=True, fused_dispatch=True)
        for m in mc._modules.values():
            m.distributed_available_fn = lambda: True
        mc.update(preds, target)
        got = {k: np.asarray(v) for k, v in mc.compute().items()}
    # identical-rank world: survivor fold == local fold
    for k in want:
        np.testing.assert_allclose(got[k], want[k], atol=1e-6, err_msg=k)
    assert mc._epoch_sync.stats.sync_degraded_folds == 1


def test_clean_run_has_zero_fault_counters(monkeypatch):
    _identical_rank_world(monkeypatch)
    preds, target = _batch(6)
    with engine_context(True), diag_context() as rec:
        m = _metric()
        m.update(preds, target)
        m.compute()
    st = m._epoch.stats
    assert st.sync_retries == 0
    assert st.sync_degraded_folds == 0
    assert rec.counts.get("sync.retry", 0) == 0
    assert rec.counts.get("sync.degraded", 0) == 0
    assert rec.counts.get("sync.fault", 0) == 0


def test_degraded_counter_rides_prometheus(monkeypatch):
    _identical_rank_world(monkeypatch)
    from torchmetrics_tpu.diag.telemetry import export_prometheus
    from torchmetrics_tpu.engine.stats import reset_engine_stats

    reset_engine_stats()
    preds, target = _batch(7)
    with engine_context(True), resilience_context(retries=0, backoff_ms=1), fault_context(
        RankDrop(rank=1)
    ):
        m = _metric()
        m.update(preds, target)
        m.compute()
    text = export_prometheus()
    assert "tm_tpu_sync_degraded_folds_total 1" in text
    reset_engine_stats()


def test_in_flight_timeout_is_not_retried(monkeypatch):
    """A watchdog escape never re-enters the collective: the abandoned call
    may still complete, and a re-issued collective would desequence the rank's
    stream against its peers. Retries>0 must not change that."""
    _identical_rank_world(monkeypatch)
    from jax.experimental import multihost_utils

    calls = {"n": 0}

    def hanging(x, tiled=False):
        calls["n"] += 1
        time.sleep(5.0)
        return np.stack([np.asarray(x)] * 2)

    monkeypatch.setattr(multihost_utils, "process_allgather", hanging)
    t0 = time.perf_counter()
    with resilience_context(deadline_ms=100, retries=5, backoff_ms=1):
        with pytest.raises(CollectiveTimeoutError) as err:
            gather_all_tensors(jnp.ones((3,)))
    assert time.perf_counter() - t0 < 2.0  # ONE deadline, not six
    assert err.value.in_flight is True
    assert err.value.retryable is False
    assert calls["n"] == 1


def test_straggler_hint_is_consumed_once():
    res_mod.note_straggler(3)
    assert res_mod.consume_straggler_hint() == 3
    assert res_mod.consume_straggler_hint() is None  # spent — stale blame impossible


def test_failed_degrade_does_not_count_a_degraded_fold(monkeypatch):
    """Both ranks of a world-2 die: the degrade itself fails — the typed error
    propagates and sync_degraded_folds stays 0 (counted on COMPLETION only)."""
    _identical_rank_world(monkeypatch)
    preds, target = _batch(8)
    with engine_context(True), resilience_context(retries=0, backoff_ms=1), fault_context(
        RankDrop(rank=1), RankDrop(rank=0)
    ):
        m = _metric()
        m.update(preds, target)
        with pytest.raises(RankUnreachableError):
            m.compute()
    assert m._epoch.stats.sync_degraded_folds == 0


def test_eager_timeout_error_env_deadline(monkeypatch):
    """The env knob alone (no context) bounds the collective."""
    _identical_rank_world(monkeypatch)
    from jax.experimental import multihost_utils

    def hanging(x, tiled=False):
        time.sleep(5.0)
        return np.stack([np.asarray(x)] * 2)

    monkeypatch.setattr(multihost_utils, "process_allgather", hanging)
    monkeypatch.setenv(res_mod.DEADLINE_ENV_VAR, "100")
    monkeypatch.setenv(res_mod.RETRIES_ENV_VAR, "0")
    t0 = time.perf_counter()
    with pytest.raises(CollectiveTimeoutError):
        gather_all_tensors(jnp.ones((2, 2)))
    assert time.perf_counter() - t0 < 2.0
