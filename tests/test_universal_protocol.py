"""Structural protocol sweep over (nearly) every metric class in the package.

The reference runs every metric through its ``MetricTester`` structural checks;
this is the breadth analogue: for each constructible class — pickle round-trip,
clone isolation, compute-cache invalidation on update, reset-to-default,
state_dict/load_state_dict round-trip, and repr. Value goldens live in the
per-domain suites; this file pins the METRIC-KERNEL contract across the zoo.
"""

from __future__ import annotations

import pickle

import numpy as np
import pytest

import jax.numpy as jnp

import torchmetrics_tpu as tm
from torchmetrics_tpu import classification as C
from torchmetrics_tpu import nominal as NOM
from torchmetrics_tpu import regression as R
from torchmetrics_tpu import retrieval as RET
from torchmetrics_tpu import text as T

N = 24
NC, NL = 4, 3
# reseeded per test (autouse fixture below) so any zoo entry reproduces in isolation
_RNG = np.random.RandomState(97)


@pytest.fixture(autouse=True)
def _seed_rng(request):
    _RNG.seed(abs(hash(request.node.name)) % (2**31))
    yield


def _binary():
    return (jnp.asarray(_RNG.rand(N).astype(np.float32)), jnp.asarray(_RNG.randint(0, 2, N)))


def _multiclass():
    p = _RNG.rand(N, NC).astype(np.float32)
    return (jnp.asarray(p / p.sum(-1, keepdims=True)), jnp.asarray(_RNG.randint(0, NC, N)))


def _multilabel():
    return (jnp.asarray(_RNG.rand(N, NL).astype(np.float32)), jnp.asarray(_RNG.randint(0, 2, (N, NL))))


def _reg():
    x = _RNG.randn(N)
    return (jnp.asarray(x + 0.1 * _RNG.randn(N)), jnp.asarray(x))


def _reg_pos():
    x = np.abs(_RNG.randn(N)) + 0.5
    return (jnp.asarray(x * (1 + 0.05 * _RNG.randn(N))), jnp.asarray(x))


def _labels_pair():
    return (jnp.asarray(_RNG.randint(0, NC, N)), jnp.asarray(_RNG.randint(0, NC, N)))


def _retrieval():
    return (
        jnp.asarray(_RNG.rand(N).astype(np.float32)),
        jnp.asarray((_RNG.rand(N) < 0.4).astype(np.int32)),
    )


def _text_pair():
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    mk = lambda: " ".join(_RNG.choice(words, 5))  # noqa: E731
    return ([mk() for _ in range(4)], [mk() for _ in range(4)])


_ZOO = [
    # classification
    (C.BinaryAccuracy, {}, _binary),
    (C.BinaryPrecision, {}, _binary),
    (C.BinaryRecall, {}, _binary),
    (C.BinaryF1Score, {}, _binary),
    (C.BinaryFBetaScore, {"beta": 2.0}, _binary),
    (C.BinarySpecificity, {}, _binary),
    (C.BinaryStatScores, {}, _binary),
    (C.BinaryCohenKappa, {}, _binary),
    (C.BinaryMatthewsCorrCoef, {}, _binary),
    (C.BinaryJaccardIndex, {}, _binary),
    (C.BinaryHammingDistance, {}, _binary),
    (C.BinaryConfusionMatrix, {}, _binary),
    (C.BinaryAUROC, {}, _binary),
    (C.BinaryAveragePrecision, {}, _binary),
    (C.BinaryPrecisionRecallCurve, {}, _binary),
    (C.BinaryROC, {}, _binary),
    (C.BinaryCalibrationError, {}, _binary),
    (C.BinaryHingeLoss, {}, _binary),
    (C.BinaryRecallAtFixedPrecision, {"min_precision": 0.5}, _binary),
    (C.BinaryPrecisionAtFixedRecall, {"min_recall": 0.5}, _binary),
    (C.BinarySpecificityAtSensitivity, {"min_sensitivity": 0.5}, _binary),
    (C.MulticlassAccuracy, {"num_classes": NC}, _multiclass),
    (C.MulticlassPrecision, {"num_classes": NC}, _multiclass),
    (C.MulticlassRecall, {"num_classes": NC}, _multiclass),
    (C.MulticlassF1Score, {"num_classes": NC}, _multiclass),
    (C.MulticlassSpecificity, {"num_classes": NC}, _multiclass),
    (C.MulticlassStatScores, {"num_classes": NC}, _multiclass),
    (C.MulticlassCohenKappa, {"num_classes": NC}, _multiclass),
    (C.MulticlassMatthewsCorrCoef, {"num_classes": NC}, _multiclass),
    (C.MulticlassJaccardIndex, {"num_classes": NC}, _multiclass),
    (C.MulticlassHammingDistance, {"num_classes": NC}, _multiclass),
    (C.MulticlassConfusionMatrix, {"num_classes": NC}, _multiclass),
    (C.MulticlassAUROC, {"num_classes": NC}, _multiclass),
    (C.MulticlassAveragePrecision, {"num_classes": NC}, _multiclass),
    (C.MulticlassPrecisionRecallCurve, {"num_classes": NC}, _multiclass),
    (C.MulticlassROC, {"num_classes": NC}, _multiclass),
    (C.MulticlassCalibrationError, {"num_classes": NC}, _multiclass),
    (C.MulticlassExactMatch, {"num_classes": NC}, _labels_pair),
    (C.MultilabelAccuracy, {"num_labels": NL}, _multilabel),
    (C.MultilabelPrecision, {"num_labels": NL}, _multilabel),
    (C.MultilabelRecall, {"num_labels": NL}, _multilabel),
    (C.MultilabelF1Score, {"num_labels": NL}, _multilabel),
    (C.MultilabelSpecificity, {"num_labels": NL}, _multilabel),
    (C.MultilabelJaccardIndex, {"num_labels": NL}, _multilabel),
    (C.MultilabelHammingDistance, {"num_labels": NL}, _multilabel),
    (C.MultilabelConfusionMatrix, {"num_labels": NL}, _multilabel),
    (C.MultilabelAUROC, {"num_labels": NL}, _multilabel),
    (C.MultilabelAveragePrecision, {"num_labels": NL}, _multilabel),
    (C.MultilabelExactMatch, {"num_labels": NL}, _multilabel),
    (C.MultilabelCoverageError, {"num_labels": NL}, _multilabel),
    (C.MultilabelRankingAveragePrecision, {"num_labels": NL}, _multilabel),
    (C.MultilabelRankingLoss, {"num_labels": NL}, _multilabel),
    (C.Dice, {"num_classes": NC}, _labels_pair),
    # regression
    (R.MeanAbsoluteError, {}, _reg),
    (R.MeanSquaredError, {}, _reg),
    (R.MeanSquaredLogError, {}, _reg_pos),
    (R.MeanAbsolutePercentageError, {}, _reg_pos),
    (R.SymmetricMeanAbsolutePercentageError, {}, _reg_pos),
    (R.WeightedMeanAbsolutePercentageError, {}, _reg_pos),
    (R.PearsonCorrCoef, {}, _reg),
    (R.SpearmanCorrCoef, {}, _reg),
    (R.KendallRankCorrCoef, {}, _reg),
    (R.ConcordanceCorrCoef, {}, _reg),
    (R.ExplainedVariance, {}, _reg),
    (R.R2Score, {}, _reg),
    (R.RelativeSquaredError, {}, _reg),
    (R.TweedieDevianceScore, {}, _reg_pos),
    (R.LogCoshError, {}, _reg),
    (R.MinkowskiDistance, {"p": 3.0}, _reg),
    # aggregation
    (tm.MeanMetric, {}, lambda: (jnp.asarray(_RNG.rand(N)),)),
    (tm.SumMetric, {}, lambda: (jnp.asarray(_RNG.rand(N)),)),
    (tm.MaxMetric, {}, lambda: (jnp.asarray(_RNG.rand(N)),)),
    (tm.MinMetric, {}, lambda: (jnp.asarray(_RNG.rand(N)),)),
    (tm.CatMetric, {}, lambda: (jnp.asarray(_RNG.rand(N)),)),
    # nominal
    (NOM.CramersV, {"num_classes": NC}, _labels_pair),
    (NOM.PearsonsContingencyCoefficient, {"num_classes": NC}, _labels_pair),
    (NOM.TheilsU, {"num_classes": NC}, _labels_pair),
    (NOM.TschuprowsT, {"num_classes": NC}, _labels_pair),
    (NOM.FleissKappa, {"mode": "counts"}, lambda: (jnp.asarray(_RNG.randint(0, 5, (N, NC)) + 1),)),
    # text (string states)
    (T.WordErrorRate, {}, _text_pair),
    (T.CharErrorRate, {}, _text_pair),
    (T.MatchErrorRate, {}, _text_pair),
    (T.WordInfoLost, {}, _text_pair),
    (T.WordInfoPreserved, {}, _text_pair),
    (T.BLEUScore, {}, lambda: ([_text_pair()[0][0]], [[_text_pair()[1][0]]])),
    (T.CHRFScore, {}, lambda: ([_text_pair()[0][0]], [[_text_pair()[1][0]]])),
]

_IDS = [cls.__name__ for cls, _, _ in _ZOO]


from tests.testers import _assert_allclose


def _tree_equal(a, b):
    a_np, b_np = np.asarray(a), np.asarray(b)
    return a_np.shape == b_np.shape and np.allclose(a_np, b_np, atol=1e-7, equal_nan=True)


def _assert_results_equal(a, b, msg=""):
    """Structure-strict equality with path-labelled failures (via testers)."""
    if isinstance(b, dict):
        assert isinstance(a, dict) and set(a) == set(b), f"{msg}: keys {set(a)} vs {set(b)}"
        for k in b:
            _assert_results_equal(a[k], b[k], msg=f"{msg}[{k}]")
        return
    if isinstance(b, (list, tuple)):
        assert len(a) == len(b), f"{msg}: length {len(a)} vs {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_results_equal(x, y, msg=f"{msg}[{i}]")
        return
    if np.asarray(b).dtype.kind == "f" and np.isnan(np.asarray(b)).any():
        assert _tree_equal(a, b), msg
        return
    _assert_allclose(a, b, atol=1e-7, msg=msg)


@pytest.mark.parametrize(("cls", "kwargs", "data"), _ZOO, ids=_IDS)
def test_protocol(cls, kwargs, data):
    import warnings

    warnings.simplefilter("ignore")
    m = cls(**kwargs)

    args1, args2 = data(), data()
    m.update(*args1)
    first = m.compute()

    # compute cache invalidates on update
    m.update(*args2)
    second = m.compute()

    # pickle round-trip preserves state and result
    clone = pickle.loads(pickle.dumps(m))
    _assert_results_equal(clone.compute(), second, msg=cls.__name__)

    # cache invalidation: a fresh metric fed args1+args2 must agree with `second`
    # (a stale cached `first` surviving the second update would diverge)
    oracle = cls(**kwargs)
    oracle.update(*args1)
    oracle.update(*args2)
    _assert_results_equal(oracle.compute(), second, msg=f"{cls.__name__} cache")

    # clone() is state-isolated
    twin = m.clone()
    twin.reset()
    assert twin.update_count == 0 and m.update_count == 2

    # state_dict/load_state_dict round-trip — state_dict carries PERSISTENT states
    # only (reference parity), so assert equality only when every array state rode it
    m.persistent(True)
    sd = m.state_dict()
    fresh = cls(**kwargs)
    fresh.load_state_dict(sd)
    if all(not isinstance(v, list) for v in (getattr(m, a) for a in m._defaults)):
        _assert_results_equal(fresh.compute(), second, msg=f"{cls.__name__} state_dict")

    # reset returns every state to its registered default
    m.reset()
    for attr, default in m._defaults.items():
        val = getattr(m, attr)
        if isinstance(default, list):
            assert val == []
        else:
            assert _tree_equal(val, default)

    # repr names the class
    assert cls.__name__ in repr(m)

    # first compute (before the second update) differed or not — either way it must
    # have been a concrete value of the same structure as the final one
    assert type(first) is type(second)


_RETRIEVAL_ZOO = [
    (RET.RetrievalMAP, {}),
    (RET.RetrievalMRR, {}),
    (RET.RetrievalPrecision, {}),
    (RET.RetrievalRecall, {}),
    (RET.RetrievalHitRate, {}),
    (RET.RetrievalFallOut, {}),
    (RET.RetrievalNormalizedDCG, {}),
    (RET.RetrievalRPrecision, {}),
]


@pytest.mark.parametrize(("cls", "kwargs"), _RETRIEVAL_ZOO, ids=[c.__name__ for c, _ in _RETRIEVAL_ZOO])
def test_retrieval_protocol(cls, kwargs):
    m = cls(**kwargs)
    scores, rel = _retrieval()
    rel = rel.at[0].set(1)  # at least one positive in query 0
    idx = jnp.asarray(np.repeat([0, 1], len(np.asarray(scores)) // 2))
    m.update(scores, rel, indexes=idx)
    val = m.compute()
    clone = pickle.loads(pickle.dumps(m))
    _assert_results_equal(clone.compute(), val, msg=cls.__name__)
    m.reset()
    assert m.update_count == 0 and m.indexes == []


@pytest.mark.parametrize(("cls", "kwargs", "data"), _ZOO, ids=_IDS)
def test_forward_epoch_equals_update_epoch(cls, kwargs, data):
    """Driving an epoch through forward() leaves the same accumulated state as
    driving it through update() — the dual-path forward contract for BOTH the
    reduce-state and full-state paths (reference ``metric.py:273-354``; the
    full-state path caches and restores registered states, so equivalence holds
    for every zoo entry — only wrappers with CHILD metrics, none of which are in
    the zoo, re-derive state)."""
    import warnings

    warnings.simplefilter("ignore")
    m_fwd = cls(**kwargs)
    m_upd = cls(**kwargs)

    batches = [data(), data(), data()]
    for args in batches:
        m_fwd(*args)
        m_upd.update(*args)
    _assert_results_equal(m_fwd.compute(), m_upd.compute(), msg=cls.__name__)


@pytest.mark.parametrize(("cls", "kwargs", "data"), _ZOO, ids=_IDS)
def test_merge_state_pairwise(cls, kwargs, data):
    """Two independently-updated replicas merged == one metric over all data, for
    every zoo entry whose states support merging."""
    import warnings

    warnings.simplefilter("ignore")
    args_a, args_b = data(), data()
    whole = cls(**kwargs)
    whole.update(*args_a)
    whole.update(*args_b)

    rep_a = cls(**kwargs)
    rep_a.update(*args_a)
    rep_b = cls(**kwargs)
    rep_b.update(*args_b)
    try:
        rep_a.merge_state(rep_b)
    except TypeError as err:
        if "Unsupported reduce_fn" not in str(err):
            raise  # a real merge bug, not the documented unsupported-states signal
        pytest.skip("states do not support merge")
    _assert_results_equal(rep_a.compute(), whole.compute(), msg=cls.__name__)
