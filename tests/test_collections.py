"""MetricCollection tests (modeled on reference ``tests/unittests/bases/test_collections.py``)."""

import numpy as np
import pytest
import jax.numpy as jnp

from torchmetrics_tpu import MetricCollection
from torchmetrics_tpu.classification import (
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
)

NUM_CLASSES = 5


def _data(n_batches=3, batch=64, seed=0):
    rng = np.random.RandomState(seed)
    return (
        [jnp.asarray(rng.randint(0, NUM_CLASSES, batch)) for _ in range(n_batches)],
        [jnp.asarray(rng.randint(0, NUM_CLASSES, batch)) for _ in range(n_batches)],
    )


def test_compute_groups_share_state_and_match_individual():
    preds, targets = _data()
    mc = MetricCollection(
        [
            MulticlassAccuracy(NUM_CLASSES, average="macro"),
            MulticlassPrecision(NUM_CLASSES, average="macro"),
            MulticlassRecall(NUM_CLASSES, average="macro"),
            MulticlassF1Score(NUM_CLASSES, average="macro"),
        ]
    )
    singles = {
        "MulticlassAccuracy": MulticlassAccuracy(NUM_CLASSES, average="macro"),
        "MulticlassPrecision": MulticlassPrecision(NUM_CLASSES, average="macro"),
        "MulticlassRecall": MulticlassRecall(NUM_CLASSES, average="macro"),
        "MulticlassF1Score": MulticlassF1Score(NUM_CLASSES, average="macro"),
    }
    for p, t in zip(preds, targets):
        mc.update(p, t)
        for m in singles.values():
            m.update(p, t)
    # all 4 share identical stat-score states → one compute group
    assert len(mc.compute_groups) == 1
    res = mc.compute()
    for k, m in singles.items():
        np.testing.assert_allclose(np.asarray(res[k]), np.asarray(m.compute()), atol=1e-7)


def test_forward_returns_batch_values():
    preds, targets = _data(seed=1)
    mc = MetricCollection([MulticlassAccuracy(NUM_CLASSES, average="micro")])
    out = mc(preds[0], targets[0])
    single = MulticlassAccuracy(NUM_CLASSES, average="micro")
    expected = single(preds[0], targets[0])
    np.testing.assert_allclose(np.asarray(out["MulticlassAccuracy"]), np.asarray(expected))


def test_prefix_postfix_and_clone():
    preds, targets = _data(seed=2)
    mc = MetricCollection([MulticlassAccuracy(NUM_CLASSES)], prefix="train_")
    mc.update(preds[0], targets[0])
    assert "train_MulticlassAccuracy" in mc.compute()
    mc2 = mc.clone(prefix="val_")
    assert "val_MulticlassAccuracy" in mc2.compute()


def test_dict_input_and_duplicate_names():
    mc = MetricCollection(
        {
            "micro": MulticlassAccuracy(NUM_CLASSES, average="micro"),
            "macro": MulticlassAccuracy(NUM_CLASSES, average="macro"),
        }
    )
    preds, targets = _data(seed=3)
    mc.update(preds[0], targets[0])
    res = mc.compute()
    assert set(res) == {"micro", "macro"}
    with pytest.raises(ValueError, match="both named"):
        MetricCollection([MulticlassAccuracy(NUM_CLASSES), MulticlassAccuracy(NUM_CLASSES)])


def test_user_specified_compute_groups():
    mc = MetricCollection(
        MulticlassRecall(NUM_CLASSES, average="macro"),
        MulticlassPrecision(NUM_CLASSES, average="macro"),
        MulticlassAccuracy(NUM_CLASSES, average="micro"),
        compute_groups=[["MulticlassRecall", "MulticlassPrecision"], ["MulticlassAccuracy"]],
    )
    preds, targets = _data(seed=4)
    for p, t in zip(preds, targets):
        mc.update(p, t)
    assert mc.compute_groups == {0: ["MulticlassRecall", "MulticlassPrecision"], 1: ["MulticlassAccuracy"]}
    singles = MulticlassPrecision(NUM_CLASSES, average="macro")
    for p, t in zip(preds, targets):
        singles.update(p, t)
    np.testing.assert_allclose(
        np.asarray(mc.compute()["MulticlassPrecision"]), np.asarray(singles.compute()), atol=1e-7
    )


def test_items_values_break_state_sharing_safely():
    mc = MetricCollection(
        [MulticlassPrecision(NUM_CLASSES, average="macro"), MulticlassRecall(NUM_CLASSES, average="macro")]
    )
    preds, targets = _data(seed=5)
    mc.update(preds[0], targets[0])
    for _, m in mc.items():  # triggers copy_state path
        assert m.update_count >= 1
    mc.update(preds[1], targets[1])  # re-establishes refs
    res = mc.compute()
    assert set(res) == {"MulticlassPrecision", "MulticlassRecall"}


def test_reset():
    mc = MetricCollection([MulticlassAccuracy(NUM_CLASSES)])
    preds, targets = _data(seed=6)
    mc.update(preds[0], targets[0])
    mc.reset()
    for m in mc.values(copy_state=False):
        assert m.update_count == 0


def test_group_members_inherit_fold_markers():
    """A leader whose dist_reduce_fx=None state was folded by merge_state propagates
    the stacked layout AND its fold marker to members — a member-side fold must not
    re-wrap the already-stacked state (regression: concatenate rank mismatch)."""
    from torchmetrics_tpu.metric import Metric

    class NoneState(Metric):
        full_state_update = True

        def __init__(self):
            super().__init__()
            self.add_state("s", jnp.zeros(3), dist_reduce_fx=None)

        def update(self, x):
            self.s = jnp.asarray(x)

        def compute(self):
            return jnp.sum(self.s)

    mc = MetricCollection({"a": NoneState(), "b": NoneState()})
    mc.update(jnp.arange(3.0))
    leader = mc._modules["a"]
    shard = NoneState()
    shard.update(jnp.arange(3.0) + 1)
    leader.merge_state(shard)  # leader state now stacked (2, 3), marked folded
    assert "s" in leader._none_folded

    for _, m in mc.items(copy_state=False):  # re-propagates leader state to members
        pass
    member = mc._modules["b"]
    assert member.s.shape == (2, 3) and "s" in member._none_folded

    shard2 = NoneState()
    shard2.update(jnp.arange(3.0) + 2)
    member.merge_state(shard2)  # crashed before fold markers travelled with states
    assert member.s.shape == (3, 3)


def test_compute_groups_at_scale():
    """An 11-metric collection discovers exactly the structurally-shareable groups:
    the stat-scores family splits by state SHAPE (micro scalars vs per-class
    vectors), curves group with curves of the same threshold grid, confmat stands
    alone — and every value matches the individually-updated metric."""
    from torchmetrics_tpu.classification import (
        MulticlassAUROC,
        MulticlassAveragePrecision,
        MulticlassConfusionMatrix,
        MulticlassF1Score,
        MulticlassJaccardIndex,
    )

    mc = MetricCollection(
        {
            "acc_macro": MulticlassAccuracy(NUM_CLASSES, average="macro"),
            "prec_macro": MulticlassPrecision(NUM_CLASSES, average="macro"),
            "rec_macro": MulticlassRecall(NUM_CLASSES, average="macro"),
            "f1_macro": MulticlassF1Score(NUM_CLASSES, average="macro"),
            "acc_micro": MulticlassAccuracy(NUM_CLASSES, average="micro"),
            "prec_micro": MulticlassPrecision(NUM_CLASSES, average="micro"),
            "auroc": MulticlassAUROC(NUM_CLASSES, thresholds=50),
            "ap": MulticlassAveragePrecision(NUM_CLASSES, thresholds=50),
            "auroc_fine": MulticlassAUROC(NUM_CLASSES, thresholds=100),
            "confmat": MulticlassConfusionMatrix(NUM_CLASSES),
            "jaccard": MulticlassJaccardIndex(NUM_CLASSES),
        }
    )
    rng = np.random.RandomState(11)
    raw = rng.rand(3, 64, NUM_CLASSES).astype(np.float64)
    preds = [jnp.asarray(r / r.sum(-1, keepdims=True)) for r in raw]  # probs (AUROC needs them)
    targets = [jnp.asarray(rng.randint(0, NUM_CLASSES, 64)) for _ in range(3)]
    for p, t in zip(preds, targets):
        mc.update(p, t)

    groups = {frozenset(v) for v in mc.compute_groups.values()}
    assert frozenset({"acc_macro", "prec_macro", "rec_macro", "f1_macro"}) in groups
    assert frozenset({"acc_micro", "prec_micro"}) in groups
    assert frozenset({"auroc", "ap"}) in groups  # same 50-threshold curve state
    assert not any("auroc_fine" in g and len(g) > 1 for g in groups)  # 100 != 50
    # confmat (C,C) and jaccard (confmat-backed) may or may not share depending on
    # state layout — whatever the grouping, VALUES must equal individual metrics
    result = mc.compute()
    for name, metric_cls, kwargs in [
        ("acc_macro", MulticlassAccuracy, {"average": "macro"}),
        ("prec_micro", MulticlassPrecision, {"average": "micro"}),
        ("auroc", MulticlassAUROC, {"thresholds": 50}),
        ("jaccard", MulticlassJaccardIndex, {}),
    ]:
        solo = metric_cls(NUM_CLASSES, **kwargs)
        for p, t in zip(preds, targets):
            solo.update(p, t)
        np.testing.assert_allclose(
            np.asarray(result[name]), np.asarray(solo.compute()), atol=1e-6, err_msg=name
        )


def test_compute_groups_survive_reset_and_second_epoch():
    mc = MetricCollection(
        [MulticlassPrecision(NUM_CLASSES, average="macro"), MulticlassRecall(NUM_CLASSES, average="macro")]
    )
    preds, targets = _data(seed=12)
    for p, t in zip(preds, targets):
        mc.update(p, t)
    first = {k: np.asarray(v) for k, v in mc.compute().items()}
    mc.reset()
    for p, t in zip(preds, targets):
        mc.update(p, t)
    second = mc.compute()
    for k in first:
        np.testing.assert_allclose(np.asarray(second[k]), first[k], atol=1e-7, err_msg=k)
    assert len(mc.compute_groups) == 1  # groups persist across epochs


def test_add_metrics_after_group_formation_rechecks():
    mc = MetricCollection([MulticlassPrecision(NUM_CLASSES, average="macro")])
    preds, targets = _data(seed=13)
    mc.update(preds[0], targets[0])
    assert mc._groups_checked
    mc.add_metrics({"recall": MulticlassRecall(NUM_CLASSES, average="macro")})
    assert not mc._groups_checked  # discovery re-runs on the next update
    mc.update(preds[1], targets[1])
    # the late-added metric missed batch 0, so its state DIFFERS from precision's
    # and they must NOT merge (value-equality grouping, reference parity)
    assert all(len(g) == 1 for g in mc.compute_groups.values())
    solo = MulticlassRecall(NUM_CLASSES, average="macro")
    solo.update(preds[1], targets[1])
    np.testing.assert_allclose(
        np.asarray(mc.compute()["recall"]), np.asarray(solo.compute()), atol=1e-6
    )


def test_fused_dispatch_group_parity_vs_per_metric_updates():
    """With the fused engine on, one collection step dispatches every compute
    group owner inside a single XLA executable; values must match per-metric
    (unfused, ungrouped) eager updates exactly — including a ragged tail."""
    from torchmetrics_tpu.classification import MulticlassConfusionMatrix
    from torchmetrics_tpu.engine import engine_context

    kw = dict(validate_args=False)
    rng = np.random.RandomState(21)
    batches = [
        (jnp.asarray(rng.rand(n, NUM_CLASSES)), jnp.asarray(rng.randint(0, NUM_CLASSES, n)))
        for n in (64, 64, 33, 64, 7)
    ]
    with engine_context(True, donate=True):
        fused = MetricCollection(
            {
                "acc_macro": MulticlassAccuracy(NUM_CLASSES, average="macro", **kw),
                "prec_macro": MulticlassPrecision(NUM_CLASSES, average="macro", **kw),
                "acc_micro": MulticlassAccuracy(NUM_CLASSES, average="micro", **kw),
                "cm": MulticlassConfusionMatrix(NUM_CLASSES, **kw),
            },
            fused_dispatch=True,
        )
        for p, t in batches:
            fused.update(p, t)
        # the stat-scores family shares one group; its owner plus the other
        # owners ran as ONE dispatch per post-discovery step
        stats = fused._fused_engine.stats
        assert stats.metrics_updated >= 3 * stats.dispatches
        out = fused.compute()
    per_metric = MetricCollection(
        {
            "acc_macro": MulticlassAccuracy(NUM_CLASSES, average="macro"),
            "prec_macro": MulticlassPrecision(NUM_CLASSES, average="macro"),
            "acc_micro": MulticlassAccuracy(NUM_CLASSES, average="micro"),
            "cm": MulticlassConfusionMatrix(NUM_CLASSES),
        },
        compute_groups=False,
        fused_dispatch=False,
    )
    for p, t in batches:
        per_metric.update(p, t)
    expected = per_metric.compute()
    for k in expected:
        np.testing.assert_allclose(
            np.asarray(out[k]), np.asarray(expected[k]), atol=1e-7, err_msg=k
        )
