"""CompositionalMetric operator sweep: the arithmetic/comparison/bitwise/unary
overload surface (``metric.py:863-999``), evaluated lazily against the eager
numpy result — plus matmul, invert, indexing, and reflected bitwise forms.
"""

from __future__ import annotations

import operator

import numpy as np
import pytest

import jax.numpy as jnp

from torchmetrics_tpu import MeanMetric, SumMetric
from torchmetrics_tpu.classification import BinaryAccuracy
from torchmetrics_tpu.metric import CompositionalMetric


def _mean_with(values):
    m = MeanMetric()
    m.update(jnp.asarray(values))
    return m


_BINARY_OPS = [
    (operator.add, "add"),
    (operator.sub, "sub"),
    (operator.mul, "mul"),
    (operator.truediv, "truediv"),
    (operator.floordiv, "floordiv"),
    (operator.mod, "mod"),
    (operator.pow, "pow"),
    (operator.eq, "eq"),
    (operator.ne, "ne"),
    (operator.lt, "lt"),
    (operator.le, "le"),
    (operator.gt, "gt"),
    (operator.ge, "ge"),
]


@pytest.mark.parametrize(("op", "name"), _BINARY_OPS, ids=[n for _, n in _BINARY_OPS])
def test_metric_op_metric(op, name):
    a = _mean_with([2.0, 4.0])   # 3.0
    b = _mean_with([1.0, 3.0])   # 2.0
    composed = op(a, b)
    assert isinstance(composed, CompositionalMetric)
    got = np.asarray(composed.compute())
    want = op(3.0, 2.0)
    np.testing.assert_allclose(got, np.asarray(want, dtype=np.float64), atol=1e-6, err_msg=name)


@pytest.mark.parametrize(("op", "name"), _BINARY_OPS, ids=[n for _, n in _BINARY_OPS])
def test_metric_op_scalar_and_reflected(op, name):
    a = _mean_with([2.0, 4.0])  # 3.0
    np.testing.assert_allclose(
        np.asarray(op(a, 2.0).compute()), op(3.0, 2.0), atol=1e-6, err_msg=f"{name} scalar"
    )
    np.testing.assert_allclose(
        np.asarray(op(5.0, a).compute()), op(5.0, 3.0), atol=1e-6, err_msg=f"r{name} scalar"
    )


def test_unary_ops_reference_quirks():
    """The reference's unary quirks, reproduced exactly: ``+m`` is abs
    (metric.py:994) and ``-m`` is ``-abs(m)`` (its ``_neg`` helper)."""
    a = _mean_with([-2.0, -4.0])  # -3.0
    np.testing.assert_allclose(float(abs(a).compute()), 3.0, atol=1e-6)
    np.testing.assert_allclose(float((+a).compute()), 3.0, atol=1e-6)
    np.testing.assert_allclose(float((-a).compute()), -3.0, atol=1e-6)  # -abs(-3)
    b = _mean_with([2.0, 4.0])  # +3.0
    np.testing.assert_allclose(float((-b).compute()), -3.0, atol=1e-6)


from torchmetrics_tpu.metric import Metric


class _IntConst(Metric):
    """Constant int32-valued metric for the bitwise/invert overload tests."""

    def __init__(self, v):
        super().__init__()
        self.add_state("v", jnp.asarray(0, dtype=jnp.int32), dist_reduce_fx="sum")
        self._init_v = v

    def update(self):
        self.v = jnp.asarray(self._init_v, dtype=jnp.int32)

    def compute(self):
        return self.v


def test_bitwise_ops_on_integer_metrics():
    a = _IntConst(6); a.update()
    b = _IntConst(3); b.update()
    np.testing.assert_allclose(int((a & b).compute()), 6 & 3)
    np.testing.assert_allclose(int((a | b).compute()), 6 | 3)
    np.testing.assert_allclose(int((a ^ b).compute()), 6 ^ 3)


def test_matmul_invert_getitem_and_reflected_bitwise():
    class Vec(Metric):
        def __init__(self, vals):
            super().__init__()
            self.add_state("v", jnp.zeros(len(vals)), dist_reduce_fx="sum")
            self._vals = jnp.asarray(vals, dtype=jnp.float64)

        def update(self):
            self.v = self._vals

        def compute(self):
            return self.v

    a = Vec([1.0, 2.0]); a.update()
    b = Vec([3.0, 4.0]); b.update()
    np.testing.assert_allclose(float((a @ b).compute()), 11.0, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray((a[1]).compute()), 2.0, atol=1e-6
    )

    m = _IntConst(6); m.update()
    np.testing.assert_allclose(int((~m).compute()), ~6)
    # reflected bitwise: plain int on the left
    np.testing.assert_allclose(int((5 & m).compute()), 5 & 6)
    np.testing.assert_allclose(int((5 | m).compute()), 5 | 6)
    np.testing.assert_allclose(int((5 ^ m).compute()), 5 ^ 6)


def test_composition_is_lazy_and_tracks_updates():
    """The DAG recomputes from CURRENT state: updating a leaf changes the result."""
    a = MeanMetric()
    b = MeanMetric()
    c = a + b
    a.update(jnp.asarray([1.0]))
    b.update(jnp.asarray([2.0]))
    np.testing.assert_allclose(float(c.compute()), 3.0, atol=1e-6)
    a.update(jnp.asarray([3.0]))  # a's mean becomes 2.0
    # no cache poke needed: composed compute() is never cached (metric.py:1002)
    np.testing.assert_allclose(float(c.compute()), 4.0, atol=1e-6)


def test_nested_composition_dag():
    a = _mean_with([4.0])
    b = _mean_with([2.0])
    expr = (a + b) * (a - b) / b  # (6 * 2) / 2 = 6
    np.testing.assert_allclose(float(expr.compute()), 6.0, atol=1e-6)


def test_composition_update_fans_out():
    """update on a composition updates every constituent metric."""
    acc_a = BinaryAccuracy()
    acc_b = BinaryAccuracy(threshold=0.3)
    both = acc_a + acc_b
    both.update(jnp.asarray([0.4, 0.9]), jnp.asarray([1, 1]))
    np.testing.assert_allclose(float(acc_a.compute()), 0.5, atol=1e-6)   # 0.4 < 0.5 miss
    np.testing.assert_allclose(float(acc_b.compute()), 1.0, atol=1e-6)   # 0.4 > 0.3 hit
    np.testing.assert_allclose(float(both.compute()), 1.5, atol=1e-6)


def test_composition_reset_fans_out():
    a = _mean_with([5.0])
    b = _mean_with([7.0])
    c = a + b
    c.reset()
    assert a.update_count == 0 and b.update_count == 0
