"""Coverage for ``check_forward_full_state_property`` and process-group forwarding
(VERDICT r1 row 7 and weak #5 tail)."""

import jax.numpy as jnp
import pytest

from torchmetrics_tpu.classification import MulticlassConfusionMatrix
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.checks import check_forward_full_state_property


def test_check_forward_full_state_property_runs(capsys):
    check_forward_full_state_property(
        MulticlassConfusionMatrix,
        init_args={"num_classes": 3},
        input_args={"preds": jnp.asarray([0, 2, 1, 1]), "target": jnp.asarray([0, 1, 2, 1])},
        num_update_to_compare=(5, 10),
        reps=2,
    )
    out = capsys.readouterr().out
    assert "Recommended setting" in out or "full_state_update=True` is required" in out


def test_check_forward_detects_disagreement(capsys):
    # a metric whose reduced-state forward genuinely diverges (updates are
    # order-dependent through a shared counter, so the two interleaved paths differ)
    class Sequenced(Metric):
        full_state_update = True
        _counter = [0]

        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("last", jnp.asarray(0.0), dist_reduce_fx="sum")

        def update(self):
            type(self)._counter[0] += 1
            self.last = self.last + type(self)._counter[0]

        def compute(self):
            return self.last

    check_forward_full_state_property(Sequenced, num_update_to_compare=(3,), reps=1)
    out = capsys.readouterr().out
    assert "`full_state_update=True` is required" in out


def test_process_group_reaches_dist_sync_fn():
    seen = {}

    def spy_sync(x, group=None):
        seen["group"] = group
        return [x, x]

    m = MulticlassConfusionMatrix(
        num_classes=2,
        dist_sync_fn=spy_sync,
        distributed_available_fn=lambda: True,
        process_group=("chip0", "chip1"),
    )
    m.update(jnp.asarray([0, 1]), jnp.asarray([0, 1]))
    m.compute()
    assert seen["group"] == ("chip0", "chip1")


def test_sync_process_group_override():
    seen = {}

    def spy_sync(x, group=None):
        seen["group"] = group
        return [x]

    m = MulticlassConfusionMatrix(num_classes=2)
    m.update(jnp.asarray([0, 1]), jnp.asarray([0, 1]))
    m.sync(dist_sync_fn=spy_sync, distributed_available=lambda: True, process_group=("sub", "world"))
    assert seen["group"] == ("sub", "world")
    m.unsync()
