"""Classification-tail tests: recall@fixed-precision, precision@fixed-recall,
specificity@sensitivity. Goldens: brute-force selection over sklearn curves."""

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import precision_recall_curve as sk_prc
from sklearn.metrics import roc_curve as sk_roc

import torchmetrics_tpu as tm
from torchmetrics_tpu.classification import (
    BinaryPrecisionAtFixedRecall,
    BinaryRecallAtFixedPrecision,
    BinarySpecificityAtSensitivity,
    MulticlassRecallAtFixedPrecision,
    MultilabelRecallAtFixedPrecision,
    MulticlassSpecificityAtSensitivity,
)
from torchmetrics_tpu.functional.classification import (
    binary_precision_at_fixed_recall,
    binary_recall_at_fixed_precision,
    binary_specificity_at_sensitivity,
    multiclass_recall_at_fixed_precision,
    multilabel_precision_at_fixed_recall,
    multiclass_specificity_at_sensitivity,
    recall_at_fixed_precision,
    specificity_at_sensitivity,
)


def _binary_case(seed=0, n=200):
    rng = np.random.RandomState(seed)
    preds = rng.rand(n)
    target = (rng.rand(n) < preds).astype(np.int64)  # informative scores
    return preds, target


def _sk_recall_at_precision(preds, target, min_precision):
    p, r, t = sk_prc(target, preds)
    best = max(
        ((rr, pp, tt) for pp, rr, tt in zip(p[:-1], r[:-1], t) if pp >= min_precision),
        default=None,
    )
    if best is None or best[0] == 0.0:
        return (best[0] if best else 0.0), 1e6
    return best[0], best[2]


class TestBinaryRecallAtFixedPrecision:
    @pytest.mark.parametrize("min_precision", [0.3, 0.5, 0.8])
    @pytest.mark.parametrize("seed", [0, 3])
    def test_vs_sklearn(self, min_precision, seed):
        preds, target = _binary_case(seed)
        recall, threshold = binary_recall_at_fixed_precision(
            jnp.asarray(preds), jnp.asarray(target), min_precision=min_precision
        )
        sk_recall, _ = _sk_recall_at_precision(preds, target, min_precision)
        assert float(recall) == pytest.approx(sk_recall, abs=1e-5)

    def test_impossible_precision(self):
        # every positive outscored by a negative: precision 1.0 is unattainable, so
        # the fallback must report recall 0 with the 1e6 sentinel threshold
        preds = jnp.array([0.1, 0.2, 0.8, 0.9])
        target = jnp.array([1, 1, 0, 0])
        recall, threshold = binary_recall_at_fixed_precision(preds, target, min_precision=1.0)
        assert float(recall) == 0.0
        assert float(threshold) == pytest.approx(1e6)

    def test_wrong_arg_name_not_leaked(self):
        with pytest.raises(ValueError, match="min_recall"):
            binary_precision_at_fixed_recall(
                jnp.zeros(4), jnp.zeros(4, dtype=jnp.int32), min_recall=2.0
            )

    def test_binned_close_to_exact(self):
        preds, target = _binary_case(5, n=2000)
        exact, _ = binary_recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), min_precision=0.5)
        binned, _ = binary_recall_at_fixed_precision(
            jnp.asarray(preds), jnp.asarray(target), min_precision=0.5, thresholds=200
        )
        assert float(binned) == pytest.approx(float(exact), abs=0.02)

    def test_modular_matches_functional(self):
        preds, target = _binary_case(7)
        metric = BinaryRecallAtFixedPrecision(min_precision=0.6)
        metric.update(jnp.asarray(preds[:100]), jnp.asarray(target[:100]))
        metric.update(jnp.asarray(preds[100:]), jnp.asarray(target[100:]))
        recall_m, thr_m = metric.compute()
        recall_f, thr_f = binary_recall_at_fixed_precision(
            jnp.asarray(preds), jnp.asarray(target), min_precision=0.6
        )
        assert float(recall_m) == pytest.approx(float(recall_f), abs=1e-6)
        assert float(thr_m) == pytest.approx(float(thr_f), abs=1e-6)


class TestBinaryPrecisionAtFixedRecall:
    @pytest.mark.parametrize("min_recall", [0.3, 0.7])
    def test_vs_sklearn(self, min_recall):
        preds, target = _binary_case(2)
        precision, _ = binary_precision_at_fixed_recall(
            jnp.asarray(preds), jnp.asarray(target), min_recall=min_recall
        )
        p, r, t = sk_prc(target, preds)
        sk_best = max(pp for pp, rr in zip(p[:-1], r[:-1]) if rr >= min_recall)
        assert float(precision) == pytest.approx(sk_best, abs=1e-5)

    def test_modular(self):
        preds, target = _binary_case(9)
        metric = BinaryPrecisionAtFixedRecall(min_recall=0.5)
        metric.update(jnp.asarray(preds), jnp.asarray(target))
        precision_m, _ = metric.compute()
        precision_f, _ = binary_precision_at_fixed_recall(jnp.asarray(preds), jnp.asarray(target), min_recall=0.5)
        assert float(precision_m) == pytest.approx(float(precision_f), abs=1e-6)


class TestBinarySpecificityAtSensitivity:
    @pytest.mark.parametrize("min_sensitivity", [0.3, 0.6, 0.9])
    def test_vs_sklearn(self, min_sensitivity):
        preds, target = _binary_case(4)
        specificity, _ = binary_specificity_at_sensitivity(
            jnp.asarray(preds), jnp.asarray(target), min_sensitivity=min_sensitivity
        )
        fpr, tpr, thr = sk_roc(target, preds)
        spec = 1 - fpr
        qual = spec[tpr >= min_sensitivity]
        assert float(specificity) == pytest.approx(qual.max(), abs=1e-5)

    def test_modular(self):
        preds, target = _binary_case(11)
        metric = BinarySpecificityAtSensitivity(min_sensitivity=0.5)
        metric.update(jnp.asarray(preds), jnp.asarray(target))
        spec_m, _ = metric.compute()
        spec_f, _ = binary_specificity_at_sensitivity(jnp.asarray(preds), jnp.asarray(target), min_sensitivity=0.5)
        assert float(spec_m) == pytest.approx(float(spec_f), abs=1e-6)


def _multiclass_case(seed=0, n=150, k=4):
    rng = np.random.RandomState(seed)
    logits = rng.randn(n, k)
    target = rng.randint(0, k, n)
    logits[np.arange(n), target] += 1.5  # informative
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    return probs, target


class TestMulticlassAndMultilabel:
    def test_multiclass_vs_per_class_binary(self):
        probs, target = _multiclass_case()
        recall, thr = multiclass_recall_at_fixed_precision(
            jnp.asarray(probs), jnp.asarray(target), num_classes=4, min_precision=0.5
        )
        assert recall.shape == (4,)
        for c in range(4):
            rec_c, _ = binary_recall_at_fixed_precision(
                jnp.asarray(probs[:, c]), jnp.asarray((target == c).astype(np.int64)), min_precision=0.5
            )
            assert float(recall[c]) == pytest.approx(float(rec_c), abs=1e-5)

    def test_multiclass_specificity(self):
        probs, target = _multiclass_case(3)
        spec, thr = multiclass_specificity_at_sensitivity(
            jnp.asarray(probs), jnp.asarray(target), num_classes=4, min_sensitivity=0.5
        )
        assert spec.shape == (4,)
        assert bool(jnp.all((spec >= 0) & (spec <= 1)))

    def test_multilabel_precision_at_recall(self):
        rng = np.random.RandomState(6)
        preds = rng.rand(100, 3)
        target = (rng.rand(100, 3) < preds).astype(np.int64)
        precision, thr = multilabel_precision_at_fixed_recall(
            jnp.asarray(preds), jnp.asarray(target), num_labels=3, min_recall=0.5
        )
        assert precision.shape == (3,)
        for lb in range(3):
            prec_l, _ = binary_precision_at_fixed_recall(
                jnp.asarray(preds[:, lb]), jnp.asarray(target[:, lb]), min_recall=0.5
            )
            assert float(precision[lb]) == pytest.approx(float(prec_l), abs=1e-5)

    def test_modular_multiclass(self):
        probs, target = _multiclass_case(8)
        metric = MulticlassRecallAtFixedPrecision(num_classes=4, min_precision=0.4)
        metric.update(jnp.asarray(probs), jnp.asarray(target))
        recall_m, _ = metric.compute()
        recall_f, _ = multiclass_recall_at_fixed_precision(
            jnp.asarray(probs), jnp.asarray(target), num_classes=4, min_precision=0.4
        )
        np.testing.assert_allclose(np.asarray(recall_m), np.asarray(recall_f), atol=1e-6)

    def test_modular_multilabel_binned(self):
        rng = np.random.RandomState(10)
        preds = rng.rand(80, 2)
        target = (rng.rand(80, 2) < preds).astype(np.int64)
        metric = MultilabelRecallAtFixedPrecision(num_labels=2, min_precision=0.5, thresholds=100)
        metric.update(jnp.asarray(preds), jnp.asarray(target))
        recall_m, _ = metric.compute()
        assert recall_m.shape == (2,)

    def test_modular_multiclass_specificity(self):
        probs, target = _multiclass_case(12)
        metric = MulticlassSpecificityAtSensitivity(num_classes=4, min_sensitivity=0.6)
        metric.update(jnp.asarray(probs), jnp.asarray(target))
        spec_m, _ = metric.compute()
        spec_f, _ = multiclass_specificity_at_sensitivity(
            jnp.asarray(probs), jnp.asarray(target), num_classes=4, min_sensitivity=0.6
        )
        np.testing.assert_allclose(np.asarray(spec_m), np.asarray(spec_f), atol=1e-6)


class TestTaskRouters:
    def test_functional_router(self):
        preds, target = _binary_case(13)
        a = recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), task="binary", min_precision=0.5)
        b = binary_recall_at_fixed_precision(jnp.asarray(preds), jnp.asarray(target), min_precision=0.5)
        assert float(a[0]) == pytest.approx(float(b[0]), abs=1e-6)
        s = specificity_at_sensitivity(jnp.asarray(preds), jnp.asarray(target), task="binary", min_sensitivity=0.5)
        assert 0.0 <= float(s[0]) <= 1.0

    def test_class_router(self):
        m = tm.RecallAtFixedPrecision(task="binary", min_precision=0.5)
        assert isinstance(m, BinaryRecallAtFixedPrecision)
        m2 = tm.SpecificityAtSensitivity(task="multiclass", num_classes=3, min_sensitivity=0.5)
        assert isinstance(m2, MulticlassSpecificityAtSensitivity)
        m3 = tm.PrecisionAtFixedRecall(task="binary", min_recall=0.5)
        assert isinstance(m3, BinaryPrecisionAtFixedRecall)

    def test_arg_validation(self):
        with pytest.raises(ValueError, match="min_precision"):
            binary_recall_at_fixed_precision(jnp.zeros(4), jnp.zeros(4, dtype=jnp.int32), min_precision=2.0)
        with pytest.raises(ValueError, match="min_sensitivity"):
            BinarySpecificityAtSensitivity(min_sensitivity=-0.5)
