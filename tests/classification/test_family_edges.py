"""Top-k matrices, ranking sweeps with ignore_index, and degenerate-input policies.

Models the reference's edge grids (``tests/unittests/classification/test_accuracy.py``
top-k cases, ``test_auroc.py``/``test_average_precision.py`` ignore_index cases, and
the zero-division behavior pinned by ``utilities/compute.py`` ``_safe_divide`` +
``_adjust_weights_safe_divide``: classes with tp+fp+fn == 0 are DROPPED from macro
averages, not averaged in as zeros).
"""

from __future__ import annotations

import warnings

import numpy as np
import pytest

import jax.numpy as jnp
from sklearn.metrics import average_precision_score as sk_ap
from sklearn.metrics import roc_auc_score as sk_auroc

from torchmetrics_tpu.classification import (
    BinaryAUROC,
    MulticlassSpecificity,
    BinaryAveragePrecision,
    BinaryF1Score,
    BinaryPrecision,
    MulticlassAUROC,
    MulticlassAccuracy,
    MulticlassAveragePrecision,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAUROC,
    MultilabelAveragePrecision,
)

NC = 5
NL = 4
NB, BS = 4, 41
_RNG = np.random.RandomState(23)


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


_mc_logits = _RNG.randn(NB, BS, NC).astype(np.float32)
_mc_probs = _softmax(_mc_logits)
_mc_target = _RNG.randint(0, NC, (NB, BS))
_bin_probs = _RNG.rand(NB, BS).astype(np.float32)
_bin_target = _RNG.randint(0, 2, (NB, BS))
_ml_probs = _RNG.rand(NB, BS, NL).astype(np.float32)
_ml_target = _RNG.randint(0, 2, (NB, BS, NL))


def _inject_ignore(target, ignore_index, frac=0.15, seed=1):
    if ignore_index is None:
        return target
    t = np.array(target)
    flat = t.reshape(-1)
    idx = np.random.RandomState(seed).choice(flat.size, int(flat.size * frac), replace=False)
    flat[idx] = ignore_index
    return t


def _update_all(metric, preds, target):
    for i in range(NB):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    return np.asarray(metric.compute())


# ------------------------------------------------------------------ top-k matrices


def _topk_onehot(probs, k):
    """(N, C) one-hot of the k highest-scoring classes per row (reference
    ``utilities/data.py select_topk``)."""
    idx = np.argsort(-probs, axis=-1, kind="stable")[:, :k]
    oh = np.zeros(probs.shape, dtype=int)
    np.put_along_axis(oh, idx, 1, axis=-1)
    return oh


def _topk_counts(probs, target, k, ignore_index=None):
    probs = probs.reshape(-1, NC)
    target = target.reshape(-1)
    if ignore_index is not None:
        keep = target != ignore_index
        probs, target = probs[keep], target[keep]
    pred_oh = _topk_onehot(probs, k)
    tgt_oh = np.zeros_like(pred_oh)
    tgt_oh[np.arange(target.size), target] = 1
    tp = (pred_oh & tgt_oh).sum(0)
    fp = (pred_oh & ~tgt_oh.astype(bool)).sum(0)
    fn = ((1 - pred_oh) & tgt_oh.astype(bool)).sum(0)
    tn = probs.shape[0] - tp - fp - fn
    return tp, fp, tn, fn


def _reduce(tp, fp, tn, fn, average, kind):
    tp, fp, tn, fn = (x.astype(np.float64) for x in (tp, fp, tn, fn))
    if kind == "accuracy":
        per = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), 0.0)
        micro = (tp.sum() + 0.0) / max((tp + fn).sum(), 1)
    elif kind == "precision":
        per = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 0.0)
        micro = tp.sum() / max((tp + fp).sum(), 1)
    elif kind == "recall":
        per = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), 0.0)
        micro = tp.sum() / max((tp + fn).sum(), 1)
    else:  # f1
        per = np.where(2 * tp + fp + fn > 0, 2 * tp / np.maximum(2 * tp + fp + fn, 1), 0.0)
        micro = 2 * tp.sum() / max((2 * tp + fp + fn).sum(), 1)
    if average == "micro":
        return micro
    support_mask = (tp + fp + fn) > 0  # reference drops dead classes from macro
    if average == "macro":
        return per[support_mask].mean()
    if average == "weighted":
        w = tp + fn
        return (per * w).sum() / max(w.sum(), 1)
    return per


@pytest.mark.parametrize("k", [1, 2, 3])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize(
    ("metric_cls", "kind"),
    [
        (MulticlassAccuracy, "accuracy"),
        (MulticlassPrecision, "precision"),
        (MulticlassRecall, "recall"),
        (MulticlassF1Score, "f1"),
    ],
)
def test_multiclass_topk_matrix(k, average, ignore_index, metric_cls, kind):
    target = _inject_ignore(_mc_target, ignore_index)
    m = metric_cls(num_classes=NC, top_k=k, average=average, ignore_index=ignore_index)
    got = float(_update_all(m, _mc_probs, target))
    tp, fp, tn, fn = _topk_counts(_mc_probs, target, k, ignore_index)
    want = _reduce(tp, fp, tn, fn, average, kind)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_topk_equals_k_classes_is_perfect_recall():
    m = MulticlassAccuracy(num_classes=NC, top_k=NC, average="micro")
    got = float(_update_all(m, _mc_probs, _mc_target))
    np.testing.assert_allclose(got, 1.0, atol=1e-7)


# ------------------------------------------------------------------ ranking sweeps


@pytest.mark.parametrize("ignore_index", [None, -1])
def test_binary_auroc_ignore_index(ignore_index):
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryAUROC(thresholds=None, ignore_index=ignore_index)
    got = float(_update_all(m, _bin_probs, target))
    p, t = _bin_probs.reshape(-1), target.reshape(-1)
    if ignore_index is not None:
        keep = t != ignore_index
        p, t = p[keep], t[keep]
    np.testing.assert_allclose(got, sk_auroc(t, p), atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
def test_binary_average_precision_ignore_index(ignore_index):
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryAveragePrecision(thresholds=None, ignore_index=ignore_index)
    got = float(_update_all(m, _bin_probs, target))
    p, t = _bin_probs.reshape(-1), target.reshape(-1)
    if ignore_index is not None:
        keep = t != ignore_index
        p, t = p[keep], t[keep]
    np.testing.assert_allclose(got, sk_ap(t, p), atol=1e-6)


@pytest.mark.parametrize("average", ["macro", "weighted"])
@pytest.mark.parametrize("ignore_index", [None, -1])
def test_multiclass_auroc_matrix(average, ignore_index):
    target = _inject_ignore(_mc_target, ignore_index, seed=2)
    m = MulticlassAUROC(num_classes=NC, average=average, thresholds=None, ignore_index=ignore_index)
    got = float(_update_all(m, _mc_probs, target))
    p, t = _mc_probs.reshape(-1, NC), target.reshape(-1)
    if ignore_index is not None:
        keep = t != ignore_index
        p, t = p[keep], t[keep]
    want = sk_auroc(t, p, multi_class="ovr", average=average, labels=list(range(NC)))
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("average", ["macro", "none"])
def test_multiclass_average_precision_matrix(average):
    m = MulticlassAveragePrecision(num_classes=NC, average=average, thresholds=None)
    got = _update_all(m, _mc_probs, _mc_target)
    p, t = _mc_probs.reshape(-1, NC), _mc_target.reshape(-1)
    per = np.asarray([sk_ap((t == c).astype(int), p[:, c]) for c in range(NC)])
    want = per.mean() if average == "macro" else per
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("average", ["macro", "micro", "none"])
def test_multilabel_average_precision_matrix(average):
    m = MultilabelAveragePrecision(num_labels=NL, average=average, thresholds=None)
    got = _update_all(m, _ml_probs, _ml_target)
    p, t = _ml_probs.reshape(-1, NL), _ml_target.reshape(-1, NL)
    if average == "micro":
        want = sk_ap(t.ravel(), p.ravel())
    else:
        per = np.asarray([sk_ap(t[:, c], p[:, c]) for c in range(NL)])
        want = per.mean() if average == "macro" else per
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("average", ["macro", "micro"])
def test_multilabel_auroc_matrix(average):
    m = MultilabelAUROC(num_labels=NL, average=average, thresholds=None)
    got = float(_update_all(m, _ml_probs, _ml_target))
    p, t = _ml_probs.reshape(-1, NL), _ml_target.reshape(-1, NL)
    if average == "micro":
        want = sk_auroc(t.ravel(), p.ravel())
    else:
        want = np.mean([sk_auroc(t[:, c], p[:, c]) for c in range(NL)])
    np.testing.assert_allclose(got, want, atol=1e-6)


# ------------------------------------------------------------------ degenerate inputs


def test_absent_class_dropped_from_macro():
    """A class never predicted and never true is dropped from the macro mean, not
    averaged in as zero (reference ``_adjust_weights_safe_divide``)."""
    preds = np.array([0, 1, 0, 1])
    target = np.array([0, 1, 0, 1])
    m = MulticlassPrecision(num_classes=3, average="macro")
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), 1.0, atol=1e-7)


def test_zero_division_is_zero_not_nan():
    """All-negative target with all-negative preds: precision 0/0 -> 0.0."""
    m = BinaryPrecision()
    m.update(jnp.zeros(8), jnp.zeros(8, dtype=jnp.int32))
    got = float(m.compute())
    assert got == 0.0 and np.isfinite(got)

    f = BinaryF1Score()
    f.update(jnp.zeros(8), jnp.zeros(8, dtype=jnp.int32))
    assert float(f.compute()) == 0.0


def test_compute_without_update_warns():
    m = MulticlassAccuracy(num_classes=3)
    with pytest.warns(UserWarning, match="before the ``update``"):
        m.compute()


def test_all_ignored_batch_is_neutral():
    """A batch whose targets are ALL ignore_index must not change the result."""
    a = MulticlassF1Score(num_classes=NC, average="macro", ignore_index=-1)
    b = MulticlassF1Score(num_classes=NC, average="macro", ignore_index=-1)
    a.update(jnp.asarray(_mc_probs[0]), jnp.asarray(_mc_target[0]))
    b.update(jnp.asarray(_mc_probs[0]), jnp.asarray(_mc_target[0]))
    b.update(jnp.asarray(_mc_probs[1]), jnp.asarray(np.full((BS,), -1)))
    np.testing.assert_allclose(float(a.compute()), float(b.compute()), atol=1e-7)


def test_single_sample_updates_accumulate():
    """Streaming one sample at a time equals one big batch."""
    whole = MulticlassRecall(num_classes=NC, average="macro")
    whole.update(jnp.asarray(_mc_probs[0]), jnp.asarray(_mc_target[0]))
    stream = MulticlassRecall(num_classes=NC, average="macro")
    for i in range(BS):
        stream.update(jnp.asarray(_mc_probs[0, i : i + 1]), jnp.asarray(_mc_target[0, i : i + 1]))
    np.testing.assert_allclose(float(whole.compute()), float(stream.compute()), atol=1e-7)


def test_perfect_and_inverted_predictions():
    perfect = MulticlassF1Score(num_classes=3, average="macro")
    perfect.update(jnp.asarray([0, 1, 2, 0]), jnp.asarray([0, 1, 2, 0]))
    np.testing.assert_allclose(float(perfect.compute()), 1.0, atol=1e-7)

    inverted = BinaryF1Score()
    inverted.update(jnp.asarray([1, 1, 0, 0]), jnp.asarray([0, 0, 1, 1]))
    np.testing.assert_allclose(float(inverted.compute()), 0.0, atol=1e-7)


def test_auroc_single_class_target_is_degenerate():
    """AUROC with only one class present: reference warns and returns 0."""
    m = BinaryAUROC(thresholds=None)
    m.update(jnp.asarray([0.1, 0.8, 0.4]), jnp.asarray([1, 1, 1]))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = float(m.compute())
    assert np.isfinite(got)


# ------------------------------------------------------------------ samplewise grids


_EXTRA = 5
_mc_md_probs = _softmax(_RNG.randn(29, NC, _EXTRA), axis=1)
_mc_md_target = _RNG.randint(0, NC, (29, _EXTRA))
_ml_md_probs = _RNG.rand(29, NL, _EXTRA)
_ml_md_target = _RNG.randint(0, 2, (29, NL, _EXTRA))


def _samplewise_counts_mc(probs, target, ignore_index=None):
    """Per-sample (tp, fp, tn, fn) over the EXTRA dim, (N, C) each."""
    hard = probs.argmax(1)  # (N, EXTRA)
    n = hard.shape[0]
    tps, fps, fns, tns = [], [], [], []
    for s in range(n):
        h, t = hard[s], target[s]
        if ignore_index is not None:
            keep = t != ignore_index
            h, t = h[keep], t[keep]
        tp = np.asarray([((h == c) & (t == c)).sum() for c in range(NC)], float)
        fp = np.asarray([((h == c) & (t != c)).sum() for c in range(NC)], float)
        fn = np.asarray([((h != c) & (t == c)).sum() for c in range(NC)], float)
        tn = len(t) - tp - fp - fn
        tps.append(tp); fps.append(fp); fns.append(fn); tns.append(tn)
    return map(np.asarray, (tps, fps, tns, fns))


def _reduce_samplewise(tp, fp, tn, fn, average, kind):
    if kind == "precision":
        per = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 0.0)
        micro_num, micro_den = tp.sum(1), (tp + fp).sum(1)
    elif kind == "recall":
        per = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), 0.0)
        micro_num, micro_den = tp.sum(1), (tp + fn).sum(1)
    elif kind == "f1":
        per = np.where(2 * tp + fp + fn > 0, 2 * tp / np.maximum(2 * tp + fp + fn, 1), 0.0)
        micro_num, micro_den = 2 * tp.sum(1), (2 * tp + fp + fn).sum(1)
    else:  # specificity
        per = np.where(tn + fp > 0, tn / np.maximum(tn + fp, 1), 0.0)
        micro_num, micro_den = tn.sum(1), (tn + fp).sum(1)
    if average == "micro":
        return micro_num / np.maximum(micro_den, 1)
    mask = (tp + fp + fn) > 0  # dead classes drop from the per-sample macro
    return np.where(mask, per, 0).sum(1) / np.maximum(mask.sum(1), 1)


@pytest.mark.parametrize("average", ["micro", "macro"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize(
    ("metric_cls", "kind"),
    [
        (MulticlassPrecision, "precision"),
        (MulticlassRecall, "recall"),
        (MulticlassF1Score, "f1"),
        (MulticlassSpecificity, "specificity"),
    ],
)
def test_multiclass_samplewise_grid(average, ignore_index, metric_cls, kind):
    target = _mc_md_target.copy()
    if ignore_index is not None:
        flat = target.reshape(-1)
        drop = np.random.RandomState(5).choice(flat.size, flat.size // 8, replace=False)
        flat[drop] = ignore_index
    m = metric_cls(
        num_classes=NC, average=average, multidim_average="samplewise", ignore_index=ignore_index
    )
    m.update(jnp.asarray(_mc_md_probs), jnp.asarray(target))
    got = np.asarray(m.compute())
    tp, fp, tn, fn = _samplewise_counts_mc(_mc_md_probs, target, ignore_index)
    want = _reduce_samplewise(tp, fp, tn, fn, average, kind)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multilabel_samplewise_grid(average):
    from torchmetrics_tpu.classification import MultilabelF1Score

    m = MultilabelF1Score(num_labels=NL, average=average, multidim_average="samplewise")
    m.update(jnp.asarray(_ml_md_probs), jnp.asarray(_ml_md_target))
    got = np.asarray(m.compute())
    hard = (_ml_md_probs > 0.5).astype(int)  # (N, NL, EXTRA)
    tp = ((hard == 1) & (_ml_md_target == 1)).sum(-1).astype(float)  # (N, NL)
    fp = ((hard == 1) & (_ml_md_target == 0)).sum(-1).astype(float)
    fn = ((hard == 0) & (_ml_md_target == 1)).sum(-1).astype(float)
    per = np.where(2 * tp + fp + fn > 0, 2 * tp / np.maximum(2 * tp + fp + fn, 1), 0.0)
    if average == "micro":
        want = 2 * tp.sum(1) / np.maximum((2 * tp + fp + fn).sum(1), 1)
    else:
        want = per.mean(1)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_samplewise_stream_appends_rows():
    """Samplewise states append one row per sample across updates."""
    m = MulticlassF1Score(num_classes=NC, average="macro", multidim_average="samplewise")
    m.update(jnp.asarray(_mc_md_probs[:10]), jnp.asarray(_mc_md_target[:10]))
    m.update(jnp.asarray(_mc_md_probs[10:]), jnp.asarray(_mc_md_target[10:]))
    got = np.asarray(m.compute())
    whole = MulticlassF1Score(num_classes=NC, average="macro", multidim_average="samplewise")
    whole.update(jnp.asarray(_mc_md_probs), jnp.asarray(_mc_md_target))
    np.testing.assert_allclose(got, np.asarray(whole.compute()), atol=1e-7)
