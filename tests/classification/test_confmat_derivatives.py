"""CohenKappa / MatthewsCorrCoef / JaccardIndex tests vs sklearn."""

import numpy as np
import pytest
import jax.numpy as jnp
from sklearn.metrics import cohen_kappa_score, jaccard_score, matthews_corrcoef as sk_mcc

from torchmetrics_tpu.classification import (
    BinaryCohenKappa,
    BinaryJaccardIndex,
    BinaryMatthewsCorrCoef,
    MulticlassCohenKappa,
    MulticlassJaccardIndex,
    MulticlassMatthewsCorrCoef,
    MultilabelJaccardIndex,
)

NUM_CLASSES = 5
NUM_LABELS = 4


def _mc_data(seed=0, n=256):
    rng = np.random.RandomState(seed)
    return rng.randint(0, NUM_CLASSES, n), rng.randint(0, NUM_CLASSES, n)


@pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
def test_multiclass_cohen_kappa(weights):
    preds, target = _mc_data(1)
    m = MulticlassCohenKappa(NUM_CLASSES, weights=weights)
    m.update(jnp.asarray(preds[:128]), jnp.asarray(target[:128]))
    m.update(jnp.asarray(preds[128:]), jnp.asarray(target[128:]))
    expected = cohen_kappa_score(target, preds, weights=weights)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_binary_cohen_kappa():
    rng = np.random.RandomState(2)
    preds = rng.rand(256)
    target = rng.randint(0, 2, 256)
    m = BinaryCohenKappa()
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = cohen_kappa_score(target, (preds > 0.5).astype(int))
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_multiclass_matthews():
    preds, target = _mc_data(3)
    m = MulticlassMatthewsCorrCoef(NUM_CLASSES)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), sk_mcc(target, preds), atol=1e-5)


def test_binary_matthews():
    rng = np.random.RandomState(4)
    preds = rng.randint(0, 2, 256)
    target = rng.randint(0, 2, 256)
    m = BinaryMatthewsCorrCoef()
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), sk_mcc(target, preds), atol=1e-5)


def test_binary_jaccard():
    rng = np.random.RandomState(5)
    preds = rng.randint(0, 2, 256)
    target = rng.randint(0, 2, 256)
    m = BinaryJaccardIndex()
    m.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(m.compute()), jaccard_score(target, preds), atol=1e-5)


@pytest.mark.parametrize("average", ["micro", "macro", "weighted"])
def test_multiclass_jaccard(average):
    preds, target = _mc_data(6)
    m = MulticlassJaccardIndex(NUM_CLASSES, average=average)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = jaccard_score(target, preds, average=average, labels=list(range(NUM_CLASSES)))
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multilabel_jaccard(average):
    rng = np.random.RandomState(7)
    preds = rng.randint(0, 2, (256, NUM_LABELS))
    target = rng.randint(0, 2, (256, NUM_LABELS))
    m = MultilabelJaccardIndex(NUM_LABELS, average=average)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = jaccard_score(target, preds, average=average)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)
