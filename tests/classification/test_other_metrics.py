"""Calibration / hinge / exact-match / ranking / fairness / dice tests vs sklearn."""

import numpy as np
import pytest
import jax.numpy as jnp
from sklearn.metrics import (
    coverage_error as sk_coverage_error,
    f1_score as sk_f1,
    hinge_loss as sk_hinge,
    label_ranking_average_precision_score as sk_lrap,
    label_ranking_loss as sk_lrl,
)

from torchmetrics_tpu.classification import (
    BinaryCalibrationError,
    BinaryFairness,
    BinaryGroupStatRates,
    BinaryHingeLoss,
    Dice,
    MulticlassCalibrationError,
    MulticlassExactMatch,
    MultilabelCoverageError,
    MultilabelExactMatch,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
)
from torchmetrics_tpu.functional.classification import dice as dice_fn

NUM_CLASSES = 5
NUM_LABELS = 4


def _ece_reference(conf, acc, n_bins=15):
    bins = np.linspace(0, 1, n_bins + 1)
    idx = np.clip(np.searchsorted(bins, conf, side="right") - 1, 0, n_bins)
    total = len(conf)
    err = 0.0
    for b in range(n_bins + 1):
        m = idx == b
        if m.sum():
            err += abs(acc[m].mean() - conf[m].mean()) * m.sum() / total
    return err


def test_binary_calibration_error_l1():
    rng = np.random.RandomState(0)
    preds = rng.rand(512)
    target = (rng.rand(512) < preds).astype(int)
    m = BinaryCalibrationError(n_bins=15, norm="l1")
    m.update(jnp.asarray(preds[:256]), jnp.asarray(target[:256]))
    m.update(jnp.asarray(preds[256:]), jnp.asarray(target[256:]))
    expected = _ece_reference(preds, target.astype(float))
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_multiclass_calibration_error():
    rng = np.random.RandomState(1)
    logits = rng.randn(512, NUM_CLASSES)
    target = rng.randint(0, NUM_CLASSES, 512)
    m = MulticlassCalibrationError(NUM_CLASSES, n_bins=10)
    m.update(jnp.asarray(logits), jnp.asarray(target))
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    conf = probs.max(1)
    acc = (probs.argmax(1) == target).astype(float)
    expected = _ece_reference(conf, acc, n_bins=10)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_binary_hinge_loss():
    rng = np.random.RandomState(2)
    preds = rng.randn(256)  # raw decision scores
    target = rng.randint(0, 2, 256)
    m = BinaryHingeLoss()
    # reference semantics: margin uses preds as-is (not sigmoid) for binary
    m.update(jnp.asarray(1 / (1 + np.exp(-preds))), jnp.asarray(target))
    # cross-check against direct formula on probabilities
    p = 1 / (1 + np.exp(-preds))
    margin = np.where(target == 1, p, -p)
    expected = np.clip(1 - margin, 0, None).mean()
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_multiclass_exact_match():
    rng = np.random.RandomState(3)
    preds = rng.randint(0, NUM_CLASSES, (32, 8))
    target = rng.randint(0, NUM_CLASSES, (32, 8))
    target[:5] = preds[:5]  # force exact rows
    m = MulticlassExactMatch(NUM_CLASSES)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = np.mean([(preds[i] == target[i]).all() for i in range(32)])
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_multilabel_exact_match():
    rng = np.random.RandomState(4)
    preds = rng.rand(64, NUM_LABELS)
    target = rng.randint(0, 2, (64, NUM_LABELS))
    m = MultilabelExactMatch(NUM_LABELS)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = np.mean([((preds[i] > 0.5).astype(int) == target[i]).all() for i in range(64)])
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-6)


def test_multilabel_coverage_error():
    rng = np.random.RandomState(5)
    preds = rng.rand(64, NUM_LABELS)
    target = rng.randint(0, 2, (64, NUM_LABELS))
    target[target.sum(1) == 0, 0] = 1  # every row needs >= 1 relevant label
    m = MultilabelCoverageError(NUM_LABELS)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = sk_coverage_error(target, preds)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_multilabel_ranking_average_precision():
    rng = np.random.RandomState(6)
    preds = rng.rand(64, NUM_LABELS)
    target = rng.randint(0, 2, (64, NUM_LABELS))
    m = MultilabelRankingAveragePrecision(NUM_LABELS)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = sk_lrap(target, preds)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_multilabel_ranking_loss():
    rng = np.random.RandomState(7)
    preds = rng.rand(64, NUM_LABELS)
    target = rng.randint(0, 2, (64, NUM_LABELS))
    m = MultilabelRankingLoss(NUM_LABELS)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = sk_lrl(target, preds)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_binary_group_stat_rates():
    preds = jnp.asarray([0, 1, 0, 1, 0, 1])
    target = jnp.asarray([0, 1, 0, 1, 0, 1])
    groups = jnp.asarray([0, 1, 0, 1, 0, 1])
    m = BinaryGroupStatRates(num_groups=2)
    m.update(preds, target, groups)
    res = m.compute()
    np.testing.assert_allclose(np.asarray(res["group_0"]), [0.0, 0.0, 1.0, 0.0])
    np.testing.assert_allclose(np.asarray(res["group_1"]), [1.0, 0.0, 0.0, 0.0])


def test_binary_fairness():
    rng = np.random.RandomState(8)
    preds = rng.rand(256)
    target = rng.randint(0, 2, 256)
    groups = rng.randint(0, 2, 256)
    m = BinaryFairness(num_groups=2, task="all")
    m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(groups))
    res = m.compute()
    labels = (preds > 0.5).astype(int)
    pr = [labels[groups == g].mean() for g in range(2)]
    dp_expected = min(pr) / max(pr)
    dp_key = [k for k in res if k.startswith("DP")][0]
    np.testing.assert_allclose(float(res[dp_key]), dp_expected, atol=1e-6)


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_dice(average):
    rng = np.random.RandomState(9)
    preds = rng.randint(0, NUM_CLASSES, 512)
    target = rng.randint(0, NUM_CLASSES, 512)
    res = dice_fn(jnp.asarray(preds), jnp.asarray(target), average=average, num_classes=NUM_CLASSES)
    # dice == f1 for label inputs
    expected = sk_f1(target, preds, average=average, labels=list(range(NUM_CLASSES)), zero_division=0)
    np.testing.assert_allclose(float(res), expected, atol=1e-5)


def test_dice_class_accumulation():
    rng = np.random.RandomState(10)
    preds = rng.randint(0, NUM_CLASSES, (4, 128))
    target = rng.randint(0, NUM_CLASSES, (4, 128))
    m = Dice(num_classes=NUM_CLASSES, average="macro")
    for p, t in zip(preds, target):
        m.update(jnp.asarray(p), jnp.asarray(t))
    expected = sk_f1(target.flatten(), preds.flatten(), average="macro",
                     labels=list(range(NUM_CLASSES)), zero_division=0)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)
