"""Reference-scale parametrization matrices for the confmat-derivative families.

Models the reference's per-family case grids (``tests/unittests/classification/
test_confusion_matrix.py``, ``test_jaccard.py``, ``test_cohen_kappa.py``,
``test_matthews_corrcoef.py``, ``test_hamming_distance.py``): input kind x
ignore_index x average/normalize, all checked against sklearn on the masked,
host-formatted data.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp
from sklearn.metrics import cohen_kappa_score as sk_kappa
from sklearn.metrics import confusion_matrix as sk_confmat
from sklearn.metrics import jaccard_score as sk_jaccard
from sklearn.metrics import matthews_corrcoef as sk_matthews

from torchmetrics_tpu.classification import (
    BinaryCohenKappa,
    BinaryConfusionMatrix,
    BinaryHammingDistance,
    BinaryJaccardIndex,
    BinaryMatthewsCorrCoef,
    MulticlassCohenKappa,
    MulticlassConfusionMatrix,
    MulticlassHammingDistance,
    MulticlassJaccardIndex,
    MulticlassMatthewsCorrCoef,
    MultilabelConfusionMatrix,
    MultilabelHammingDistance,
    MultilabelJaccardIndex,
    MultilabelMatthewsCorrCoef,
)

NC = 5
NL = 4
NB, BS = 4, 33
_RNG = np.random.RandomState(11)

_bin_probs = _RNG.rand(NB, BS).astype(np.float32)
_bin_logits = _RNG.randn(NB, BS).astype(np.float32)
_bin_labels = _RNG.randint(0, 2, (NB, BS))
_bin_target = _RNG.randint(0, 2, (NB, BS))

_mc_logits = _RNG.randn(NB, BS, NC).astype(np.float32)
_mc_labels = _RNG.randint(0, NC, (NB, BS))
_mc_target = _RNG.randint(0, NC, (NB, BS))

_ml_probs = _RNG.rand(NB, BS, NL).astype(np.float32)
_ml_labels = _RNG.randint(0, 2, (NB, BS, NL))
_ml_target = _RNG.randint(0, 2, (NB, BS, NL))


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _inject_ignore(target, ignore_index, frac=0.12, seed=0):
    if ignore_index is None:
        return target
    t = np.array(target)
    flat = t.reshape(-1)
    idx = np.random.RandomState(seed).choice(flat.size, int(flat.size * frac), replace=False)
    flat[idx] = ignore_index
    return t


def _mask(hard, target, ignore_index):
    hard = np.asarray(hard).reshape(-1)
    target = np.asarray(target).reshape(-1)
    if ignore_index is None:
        return hard, target
    keep = target != ignore_index
    return hard[keep], target[keep]


def _update_all(metric, preds, target):
    for i in range(NB):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    return np.asarray(metric.compute())


def _bin_hard(kind):
    if kind == "labels":
        return _bin_labels
    p = _sigmoid(_bin_logits) if kind == "logits" else _bin_probs
    return (p > 0.5).astype(int)


# ------------------------------------------------------------------ confusion matrix


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("normalize", [None, "true", "pred", "all"])
def test_binary_confusion_matrix_matrix(kind, ignore_index, normalize):
    preds = {"probs": _bin_probs, "logits": _bin_logits, "labels": _bin_labels}[kind]
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryConfusionMatrix(ignore_index=ignore_index, normalize=normalize)
    got = _update_all(m, preds, target)

    hard, t = _mask(_bin_hard(kind), target, ignore_index)
    want = sk_confmat(t, hard, labels=[0, 1]).astype(np.float64)
    if normalize == "true":
        want = want / np.maximum(want.sum(1, keepdims=True), 1e-12)
    elif normalize == "pred":
        want = want / np.maximum(want.sum(0, keepdims=True), 1e-12)
    elif normalize == "all":
        want = want / max(want.sum(), 1e-12)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1, 2])
@pytest.mark.parametrize("normalize", [None, "true", "pred", "all"])
def test_multiclass_confusion_matrix_matrix(ignore_index, normalize):
    # ignore_index=2 (a VALID class id) must drop those samples entirely
    target = _inject_ignore(_mc_target, ignore_index, seed=3)
    m = MulticlassConfusionMatrix(num_classes=NC, ignore_index=ignore_index, normalize=normalize)
    got = _update_all(m, _mc_logits, target)

    hard, t = _mask(_mc_logits.argmax(-1), target, ignore_index)
    want = sk_confmat(t, hard, labels=list(range(NC))).astype(np.float64)
    if normalize == "true":
        sums = want.sum(1, keepdims=True)
    elif normalize == "pred":
        sums = want.sum(0, keepdims=True)
    elif normalize == "all":
        sums = np.asarray(want.sum())
    else:
        sums = None
    if sums is not None:
        want = want / np.where(np.asarray(sums) == 0, 1.0, sums)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
def test_multilabel_confusion_matrix_matrix(ignore_index):
    target = _inject_ignore(_ml_target, ignore_index, seed=5)
    m = MultilabelConfusionMatrix(num_labels=NL, ignore_index=ignore_index)
    got = _update_all(m, _ml_probs, target)
    hard = (_ml_probs > 0.5).astype(int).reshape(-1, NL)
    t = target.reshape(-1, NL)
    for lab in range(NL):
        h, tt = _mask(hard[:, lab], t[:, lab], ignore_index)
        want = sk_confmat(tt, h, labels=[0, 1])
        np.testing.assert_allclose(got[lab], want, atol=1e-6, err_msg=f"label {lab}")


# ------------------------------------------------------------------ jaccard


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
def test_binary_jaccard_matrix(kind, ignore_index):
    preds = {"probs": _bin_probs, "logits": _bin_logits, "labels": _bin_labels}[kind]
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryJaccardIndex(ignore_index=ignore_index)
    got = float(_update_all(m, preds, target))
    hard, t = _mask(_bin_hard(kind), target, ignore_index)
    want = sk_jaccard(t, hard, zero_division=0)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
def test_multiclass_jaccard_matrix(ignore_index, average):
    target = _inject_ignore(_mc_target, ignore_index, seed=7)
    m = MulticlassJaccardIndex(num_classes=NC, average=average, ignore_index=ignore_index)
    got = _update_all(m, _mc_logits, target)
    hard, t = _mask(_mc_logits.argmax(-1), target, ignore_index)
    avg = None if average == "none" else average
    want = sk_jaccard(t, hard, labels=list(range(NC)), average=avg, zero_division=0)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("average", ["micro", "macro", "none"])
def test_multilabel_jaccard_matrix(average):
    m = MultilabelJaccardIndex(num_labels=NL, average=average)
    got = _update_all(m, _ml_probs, _ml_target)
    hard = (_ml_probs > 0.5).astype(int).reshape(-1, NL)
    t = _ml_target.reshape(-1, NL)
    avg = None if average == "none" else average
    want = sk_jaccard(t, hard, average=avg, zero_division=0)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)


# ------------------------------------------------------------------ cohen kappa


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
def test_binary_cohen_kappa_matrix(kind, ignore_index, weights):
    preds = {"probs": _bin_probs, "logits": _bin_logits, "labels": _bin_labels}[kind]
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryCohenKappa(ignore_index=ignore_index, weights=weights)
    got = float(_update_all(m, preds, target))
    hard, t = _mask(_bin_hard(kind), target, ignore_index)
    want = sk_kappa(t, hard, weights=weights)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
def test_multiclass_cohen_kappa_matrix(ignore_index, weights):
    target = _inject_ignore(_mc_target, ignore_index, seed=9)
    m = MulticlassCohenKappa(num_classes=NC, ignore_index=ignore_index, weights=weights)
    got = float(_update_all(m, _mc_logits, target))
    hard, t = _mask(_mc_logits.argmax(-1), target, ignore_index)
    want = sk_kappa(t, hard, labels=list(range(NC)), weights=weights)
    np.testing.assert_allclose(got, want, atol=1e-6)


# ------------------------------------------------------------------ matthews


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
def test_binary_matthews_matrix(kind, ignore_index):
    preds = {"probs": _bin_probs, "logits": _bin_logits, "labels": _bin_labels}[kind]
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryMatthewsCorrCoef(ignore_index=ignore_index)
    got = float(_update_all(m, preds, target))
    hard, t = _mask(_bin_hard(kind), target, ignore_index)
    np.testing.assert_allclose(got, sk_matthews(t, hard), atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
def test_multiclass_matthews_matrix(ignore_index):
    target = _inject_ignore(_mc_target, ignore_index, seed=13)
    m = MulticlassMatthewsCorrCoef(num_classes=NC, ignore_index=ignore_index)
    got = float(_update_all(m, _mc_logits, target))
    hard, t = _mask(_mc_logits.argmax(-1), target, ignore_index)
    np.testing.assert_allclose(got, sk_matthews(t, hard), atol=1e-6)


def test_multilabel_matthews_matrix():
    """Reference multilabel MCC folds every label into one global 2x2 confmat —
    equals binary MCC over the flattened label matrix."""
    m = MultilabelMatthewsCorrCoef(num_labels=NL)
    got = float(_update_all(m, _ml_probs, _ml_target))
    hard = (_ml_probs > 0.5).astype(int).reshape(-1)
    want = sk_matthews(_ml_target.reshape(-1), hard)
    np.testing.assert_allclose(got, want, atol=1e-6)


# ------------------------------------------------------------------ hamming


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
def test_binary_hamming_matrix(kind, ignore_index):
    preds = {"probs": _bin_probs, "logits": _bin_logits, "labels": _bin_labels}[kind]
    target = _inject_ignore(_bin_target, ignore_index)
    m = BinaryHammingDistance(ignore_index=ignore_index)
    got = float(_update_all(m, preds, target))
    hard, t = _mask(_bin_hard(kind), target, ignore_index)
    np.testing.assert_allclose(got, (hard != t).mean(), atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multiclass_hamming_matrix(ignore_index, average):
    """Hamming distance == 1 - accuracy under the same averaging (reference
    ``functional/classification/hamming.py`` reduce)."""
    target = _inject_ignore(_mc_target, ignore_index, seed=17)
    m = MulticlassHammingDistance(num_classes=NC, average=average, ignore_index=ignore_index)
    got = float(_update_all(m, _mc_logits, target))
    hard, t = _mask(_mc_logits.argmax(-1), target, ignore_index)
    if average == "micro":
        want = (hard != t).mean()
    else:  # macro: 1 - mean per-class recall
        recalls = [((hard == c) & (t == c)).sum() / max((t == c).sum(), 1) for c in range(NC)]
        want = 1.0 - np.mean(recalls)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("average", ["micro", "macro", "none"])
def test_multilabel_hamming_matrix(average):
    m = MultilabelHammingDistance(num_labels=NL, average=average)
    got = _update_all(m, _ml_probs, _ml_target)
    hard = (_ml_probs > 0.5).astype(int).reshape(-1, NL)
    t = _ml_target.reshape(-1, NL)
    per_label = (hard != t).mean(axis=0)
    if average == "micro":
        want = (hard != t).mean()
    elif average == "macro":
        want = per_label.mean()
    else:
        want = per_label
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)
