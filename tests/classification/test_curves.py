"""PR-curve / ROC / AUROC / AveragePrecision tests vs sklearn."""

import numpy as np
import pytest
import jax.numpy as jnp
from sklearn.metrics import (
    average_precision_score,
    precision_recall_curve as sk_prc,
    roc_auc_score,
    roc_curve as sk_roc_curve,
)

from torchmetrics_tpu.classification import (
    BinaryAUROC,
    BinaryAveragePrecision,
    BinaryPrecisionRecallCurve,
    BinaryROC,
    MulticlassAUROC,
    MulticlassAveragePrecision,
    MultilabelAUROC,
)

NUM_CLASSES = 5
NUM_LABELS = 4


def _binary_stream(n_batches=4, batch=64, seed=0):
    rng = np.random.RandomState(seed)
    return rng.rand(n_batches, batch), rng.randint(0, 2, (n_batches, batch))


def test_binary_pr_curve_exact_vs_sklearn():
    preds, target = _binary_stream()
    m = BinaryPrecisionRecallCurve(thresholds=None)
    for p, t in zip(preds, target):
        m.update(jnp.asarray(p), jnp.asarray(t))
    precision, recall, thresholds = m.compute()
    skp, skr, skt = sk_prc(target.flatten(), preds.flatten())
    np.testing.assert_allclose(np.asarray(precision), skp, atol=1e-6)
    np.testing.assert_allclose(np.asarray(recall), skr, atol=1e-6)
    np.testing.assert_allclose(np.asarray(thresholds), skt, atol=1e-6)


def test_binary_roc_exact_vs_sklearn():
    preds, target = _binary_stream(seed=1)
    m = BinaryROC(thresholds=None)
    for p, t in zip(preds, target):
        m.update(jnp.asarray(p), jnp.asarray(t))
    fpr, tpr, thresholds = m.compute()
    skf, skt_, _ = sk_roc_curve(target.flatten(), preds.flatten(), drop_intermediate=False)
    np.testing.assert_allclose(np.asarray(fpr), skf, atol=1e-6)
    np.testing.assert_allclose(np.asarray(tpr), skt_, atol=1e-6)


@pytest.mark.parametrize("thresholds", [None, 200])
def test_binary_auroc(thresholds):
    preds, target = _binary_stream(seed=2)
    m = BinaryAUROC(thresholds=thresholds)
    for p, t in zip(preds, target):
        m.update(jnp.asarray(p), jnp.asarray(t))
    expected = roc_auc_score(target.flatten(), preds.flatten())
    atol = 1e-6 if thresholds is None else 1e-2
    np.testing.assert_allclose(float(m.compute()), expected, atol=atol)


@pytest.mark.parametrize("thresholds", [None, 200])
def test_binary_average_precision(thresholds):
    preds, target = _binary_stream(seed=3)
    m = BinaryAveragePrecision(thresholds=thresholds)
    for p, t in zip(preds, target):
        m.update(jnp.asarray(p), jnp.asarray(t))
    expected = average_precision_score(target.flatten(), preds.flatten())
    atol = 1e-6 if thresholds is None else 1e-2
    np.testing.assert_allclose(float(m.compute()), expected, atol=atol)


@pytest.mark.parametrize("average", ["macro", "weighted", None])
@pytest.mark.parametrize("thresholds", [None, 200])
def test_multiclass_auroc(average, thresholds):
    rng = np.random.RandomState(4)
    logits = rng.randn(2, 128, NUM_CLASSES)
    target = rng.randint(0, NUM_CLASSES, (2, 128))
    m = MulticlassAUROC(NUM_CLASSES, average=average, thresholds=thresholds)
    for p, t in zip(logits, target):
        m.update(jnp.asarray(p), jnp.asarray(t))
    res = m.compute()
    probs = np.exp(logits.reshape(-1, NUM_CLASSES))
    probs /= probs.sum(1, keepdims=True)
    if average is None:
        assert res.shape == (NUM_CLASSES,)
    else:
        expected = roc_auc_score(target.flatten(), probs, multi_class="ovr", average=average)
        atol = 1e-5 if thresholds is None else 1e-2
        np.testing.assert_allclose(float(res), expected, atol=atol)


def test_multiclass_average_precision_macro():
    rng = np.random.RandomState(5)
    logits = rng.randn(256, NUM_CLASSES)
    target = rng.randint(0, NUM_CLASSES, 256)
    m = MulticlassAveragePrecision(NUM_CLASSES, average="macro", thresholds=None)
    m.update(jnp.asarray(logits), jnp.asarray(target))
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    onehot = np.eye(NUM_CLASSES)[target]
    expected = np.mean([average_precision_score(onehot[:, c], probs[:, c]) for c in range(NUM_CLASSES)])
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multilabel_auroc(average):
    rng = np.random.RandomState(6)
    preds = rng.rand(256, NUM_LABELS)
    target = rng.randint(0, 2, (256, NUM_LABELS))
    m = MultilabelAUROC(NUM_LABELS, average=average, thresholds=None)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    expected = roc_auc_score(target, preds, average=average)
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-5)


def test_binned_state_merges_across_instances():
    preds, target = _binary_stream(seed=7)
    m_a = BinaryAUROC(thresholds=100)
    m_b = BinaryAUROC(thresholds=100)
    m_all = BinaryAUROC(thresholds=100)
    for i, (p, t) in enumerate(zip(preds, target)):
        (m_a if i % 2 == 0 else m_b).update(jnp.asarray(p), jnp.asarray(t))
        m_all.update(jnp.asarray(p), jnp.asarray(t))
    m_a.merge_state(m_b)
    np.testing.assert_allclose(float(m_a.compute()), float(m_all.compute()), atol=1e-7)


def test_pr_curve_binned_ignore_index():
    rng = np.random.RandomState(8)
    preds = rng.rand(300)
    target = rng.choice([0, 1, -1], 300)
    m = BinaryAveragePrecision(thresholds=500, ignore_index=-1)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    keep = target != -1
    expected = average_precision_score(target[keep], preds[keep])
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-2)
