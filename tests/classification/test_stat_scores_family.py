"""Classification StatScores-family tests vs sklearn golden references.

Mirrors the reference's three-level MetricTester checks
(``tests/unittests/helpers/testers.py:77-227``): (a) per-batch ``forward`` values,
(b) final ``compute`` over all data, (c) distributed accumulation — here emulated by
merging two independently-updated metric instances via ``merge_state`` (the TPU-native
promotion of ``_reduce_states``).
"""

import numpy as np
import pytest
import jax.numpy as jnp
from sklearn.metrics import (
    accuracy_score,
    confusion_matrix as sk_confusion_matrix,
    f1_score as sk_f1_score,
    fbeta_score as sk_fbeta_score,
    precision_score as sk_precision_score,
    recall_score as sk_recall_score,
    multilabel_confusion_matrix as sk_multilabel_confusion_matrix,
)

from torchmetrics_tpu.classification import (
    BinaryAccuracy,
    BinaryConfusionMatrix,
    BinaryF1Score,
    BinaryPrecision,
    BinaryRecall,
    BinarySpecificity,
    BinaryStatScores,
    MulticlassAccuracy,
    MulticlassConfusionMatrix,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelAccuracy,
    MultilabelConfusionMatrix,
    MultilabelF1Score,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification import (
    binary_accuracy,
    binary_fbeta_score,
    binary_stat_scores,
    multiclass_accuracy,
    multiclass_confusion_matrix,
    multiclass_stat_scores,
    multilabel_accuracy,
    multilabel_stat_scores,
)

NUM_CLASSES = 5
NUM_LABELS = 4
NUM_BATCHES = 4
BATCH_SIZE = 32


def _binary_data(probs=True):
    rng = np.random.RandomState(42)
    preds = rng.rand(NUM_BATCHES, BATCH_SIZE) if probs else rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
    target = rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
    return jnp.asarray(preds), jnp.asarray(target)


def _multiclass_data(logits=True):
    rng = np.random.RandomState(42)
    if logits:
        preds = rng.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES)
        preds = jnp.asarray(preds)
    else:
        preds = jnp.asarray(rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)))
    return preds, target


def _multilabel_data():
    rng = np.random.RandomState(42)
    preds = jnp.asarray(rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_LABELS))
    target = jnp.asarray(rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_LABELS)))
    return preds, target


def _binary_labels(preds):
    return (np.asarray(preds) > 0.5).astype(int) if np.asarray(preds).dtype.kind == "f" else np.asarray(preds)


def _run_class_test(metric_factory, preds, target, ref_fn, atol=1e-6):
    """Three-level check: batch forward, full compute, 2-way merged compute."""
    metric = metric_factory()
    for i in range(NUM_BATCHES):
        batch_val = metric(preds[i], target[i])
        ref_batch = ref_fn(preds[i], target[i])
        np.testing.assert_allclose(np.asarray(batch_val), ref_batch, atol=atol)
    total = metric.compute()
    all_preds = np.concatenate([np.asarray(preds[i]) for i in range(NUM_BATCHES)])
    all_target = np.concatenate([np.asarray(target[i]) for i in range(NUM_BATCHES)])
    ref_total = ref_fn(all_preds, all_target)
    np.testing.assert_allclose(np.asarray(total), ref_total, atol=atol)

    # emulate 2-process accumulation with merge_state
    m_a, m_b = metric_factory(), metric_factory()
    for i in range(NUM_BATCHES):
        (m_a if i % 2 == 0 else m_b).update(preds[i], target[i])
    m_a.merge_state(m_b)
    np.testing.assert_allclose(np.asarray(m_a.compute()), ref_total, atol=atol)


# ------------------------------------------------------------------------------ binary


class TestBinaryFamily:
    def test_stat_scores(self):
        preds, target = _binary_data()

        def ref(p, t):
            p, t = _binary_labels(p), np.asarray(t)
            tp = ((p == 1) & (t == 1)).sum()
            fp = ((p == 1) & (t == 0)).sum()
            tn = ((p == 0) & (t == 0)).sum()
            fn = ((p == 0) & (t == 1)).sum()
            return np.array([tp, fp, tn, fn, tp + fn])

        _run_class_test(BinaryStatScores, preds, target, ref)

    def test_functional_stat_scores_matches_class(self):
        preds, target = _binary_data()
        res = binary_stat_scores(preds.flatten(), target.flatten())
        m = BinaryStatScores()
        for i in range(NUM_BATCHES):
            m.update(preds[i], target[i])
        np.testing.assert_allclose(np.asarray(res), np.asarray(m.compute()))

    @pytest.mark.parametrize(
        ("factory", "sk_fn"),
        [
            (BinaryAccuracy, accuracy_score),
            (BinaryPrecision, lambda t, p: sk_precision_score(t, p, zero_division=0)),
            (BinaryRecall, lambda t, p: sk_recall_score(t, p, zero_division=0)),
            (BinaryF1Score, lambda t, p: sk_f1_score(t, p, zero_division=0)),
        ],
    )
    def test_scores_vs_sklearn(self, factory, sk_fn):
        preds, target = _binary_data()

        def ref(p, t):
            return sk_fn(np.asarray(t), _binary_labels(p))

        _run_class_test(factory, preds, target, ref)

    def test_specificity(self):
        preds, target = _binary_data()

        def ref(p, t):
            cm = sk_confusion_matrix(np.asarray(t), _binary_labels(p), labels=[0, 1])
            tn, fp = cm[0, 0], cm[0, 1]
            return tn / (tn + fp) if (tn + fp) else 0.0

        _run_class_test(BinarySpecificity, preds, target, ref)

    def test_confusion_matrix(self):
        preds, target = _binary_data()

        def ref(p, t):
            return sk_confusion_matrix(np.asarray(t), _binary_labels(p), labels=[0, 1])

        _run_class_test(BinaryConfusionMatrix, preds, target, ref)

    def test_fbeta_logits_autosigmoid(self):
        rng = np.random.RandomState(7)
        logits = jnp.asarray(rng.randn(64) * 3)
        target = jnp.asarray(rng.randint(0, 2, 64))
        probs = 1 / (1 + np.exp(-np.asarray(logits)))
        expected = sk_fbeta_score(np.asarray(target), probs > 0.5, beta=2.0, zero_division=0)
        res = binary_fbeta_score(logits, target, beta=2.0)
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_ignore_index(self):
        rng = np.random.RandomState(3)
        preds = jnp.asarray(rng.rand(128))
        target = jnp.asarray(rng.choice([0, 1, -1], 128))
        keep = np.asarray(target) != -1
        expected = accuracy_score(np.asarray(target)[keep], _binary_labels(preds)[keep])
        res = binary_accuracy(preds, target, ignore_index=-1)
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_samplewise(self):
        rng = np.random.RandomState(5)
        preds = jnp.asarray(rng.rand(8, 32))
        target = jnp.asarray(rng.randint(0, 2, (8, 32)))
        res = binary_accuracy(preds, target, multidim_average="samplewise")
        expected = np.array(
            [accuracy_score(np.asarray(target[i]), _binary_labels(preds[i])) for i in range(8)]
        )
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


# --------------------------------------------------------------------------- multiclass


class TestMulticlassFamily:
    @pytest.mark.parametrize("average", ["micro", "macro", "weighted", None])
    def test_stat_scores_shapes_and_totals(self, average):
        preds, target = _multiclass_data()
        res = multiclass_stat_scores(
            preds.reshape(-1, NUM_CLASSES), target.flatten(), NUM_CLASSES, average=average
        )
        labels = np.argmax(np.asarray(preds.reshape(-1, NUM_CLASSES)), axis=1)
        t = np.asarray(target.flatten())
        if average is None:
            assert res.shape == (NUM_CLASSES, 5)
            for c in range(NUM_CLASSES):
                tp = ((labels == c) & (t == c)).sum()
                fn = ((labels != c) & (t == c)).sum()
                np.testing.assert_allclose(np.asarray(res[c, 0]), tp)
                np.testing.assert_allclose(np.asarray(res[c, 3]), fn)
        elif average == "micro":
            np.testing.assert_allclose(np.asarray(res[0]), (labels == t).sum())

    @pytest.mark.parametrize(
        ("average", "sk_ref"),
        [
            ("micro", lambda t, p: accuracy_score(t, p)),
            ("macro", lambda t, p: sk_recall_score(t, p, average="macro", labels=list(range(NUM_CLASSES)), zero_division=0)),
            ("weighted", lambda t, p: sk_recall_score(t, p, average="weighted", labels=list(range(NUM_CLASSES)), zero_division=0)),
        ],
    )
    def test_accuracy_vs_sklearn(self, average, sk_ref):
        preds, target = _multiclass_data()

        def ref(p, t):
            labels = np.argmax(np.asarray(p), axis=-1)
            return sk_ref(np.asarray(t), labels)

        _run_class_test(lambda: MulticlassAccuracy(NUM_CLASSES, average=average), preds, target, ref)

    @pytest.mark.parametrize(
        ("factory", "sk_fn"),
        [
            (
                lambda: MulticlassPrecision(NUM_CLASSES, average="macro"),
                lambda t, p: sk_precision_score(t, p, average="macro", labels=list(range(NUM_CLASSES)), zero_division=0),
            ),
            (
                lambda: MulticlassRecall(NUM_CLASSES, average="macro"),
                lambda t, p: sk_recall_score(t, p, average="macro", labels=list(range(NUM_CLASSES)), zero_division=0),
            ),
            (
                lambda: MulticlassF1Score(NUM_CLASSES, average="macro"),
                lambda t, p: sk_f1_score(t, p, average="macro", labels=list(range(NUM_CLASSES)), zero_division=0),
            ),
        ],
    )
    def test_prf_vs_sklearn(self, factory, sk_fn):
        preds, target = _multiclass_data()

        def ref(p, t):
            labels = np.argmax(np.asarray(p), axis=-1)
            return sk_fn(np.asarray(t), labels)

        _run_class_test(factory, preds, target, ref)

    def test_confusion_matrix_vs_sklearn(self, ):
        preds, target = _multiclass_data()

        def ref(p, t):
            labels = np.argmax(np.asarray(p), axis=-1)
            return sk_confusion_matrix(np.asarray(t), labels, labels=list(range(NUM_CLASSES)))

        _run_class_test(lambda: MulticlassConfusionMatrix(NUM_CLASSES), preds, target, ref)

    def test_confusion_matrix_normalize(self):
        preds, target = _multiclass_data()
        p, t = preds.reshape(-1, NUM_CLASSES), target.flatten()
        res = multiclass_confusion_matrix(p, t, NUM_CLASSES, normalize="true")
        labels = np.argmax(np.asarray(p), axis=-1)
        expected = sk_confusion_matrix(np.asarray(t), labels, labels=list(range(NUM_CLASSES)), normalize="true")
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_top_k(self):
        rng = np.random.RandomState(11)
        preds = jnp.asarray(rng.randn(256, NUM_CLASSES))
        target = jnp.asarray(rng.randint(0, NUM_CLASSES, 256))
        res = multiclass_accuracy(preds, target, NUM_CLASSES, average="micro", top_k=2)
        topk = np.argsort(-np.asarray(preds), axis=1)[:, :2]
        expected = np.mean([t in row for t, row in zip(np.asarray(target), topk)])
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_ignore_index(self):
        rng = np.random.RandomState(13)
        preds = jnp.asarray(rng.randn(256, NUM_CLASSES))
        target = jnp.asarray(rng.randint(0, NUM_CLASSES, 256).astype(np.int32))
        target = jnp.where(jnp.asarray(rng.rand(256)) < 0.2, -100, target)
        keep = np.asarray(target) != -100
        labels = np.argmax(np.asarray(preds), axis=-1)
        expected = accuracy_score(np.asarray(target)[keep], labels[keep])
        res = multiclass_accuracy(preds, target, NUM_CLASSES, average="micro", ignore_index=-100)
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    def test_samplewise(self):
        rng = np.random.RandomState(17)
        preds = jnp.asarray(rng.randint(0, NUM_CLASSES, (8, 64)))
        target = jnp.asarray(rng.randint(0, NUM_CLASSES, (8, 64)))
        res = multiclass_accuracy(preds, target, NUM_CLASSES, average="micro", multidim_average="samplewise")
        expected = np.array([accuracy_score(np.asarray(target[i]), np.asarray(preds[i])) for i in range(8)])
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)

    @pytest.mark.parametrize("average", ["macro", "weighted"])
    def test_samplewise_macro_weighted(self, average):
        rng = np.random.RandomState(19)
        preds = jnp.asarray(rng.randint(0, NUM_CLASSES, (8, 64)))
        target = jnp.asarray(rng.randint(0, NUM_CLASSES, (8, 64)))
        res = multiclass_accuracy(preds, target, NUM_CLASSES, average=average, multidim_average="samplewise")
        assert res.shape == (8,)
        expected = np.array(
            [
                sk_recall_score(
                    np.asarray(target[i]), np.asarray(preds[i]), average=average,
                    labels=list(range(NUM_CLASSES)), zero_division=0,
                )
                for i in range(8)
            ]
        )
        np.testing.assert_allclose(np.asarray(res), expected, atol=1e-6)


# --------------------------------------------------------------------------- multilabel


class TestMultilabelFamily:
    def test_stat_scores(self):
        preds, target = _multilabel_data()
        res = multilabel_stat_scores(
            preds.reshape(-1, NUM_LABELS), target.reshape(-1, NUM_LABELS), NUM_LABELS, average=None
        )
        cms = sk_multilabel_confusion_matrix(
            np.asarray(target.reshape(-1, NUM_LABELS)), np.asarray(preds.reshape(-1, NUM_LABELS)) > 0.5
        )
        for c in range(NUM_LABELS):
            tn, fp, fn, tp = cms[c].ravel()
            np.testing.assert_allclose(np.asarray(res[c]), [tp, fp, tn, fn, tp + fn])

    @pytest.mark.parametrize("average", ["micro", "macro"])
    def test_accuracy(self, average):
        preds, target = _multilabel_data()

        def ref(p, t):
            p = (np.asarray(p) > 0.5).astype(int).reshape(-1, NUM_LABELS)
            t = np.asarray(t).reshape(-1, NUM_LABELS)
            if average == "micro":
                return (p == t).mean()
            return np.mean([(p[:, c] == t[:, c]).mean() for c in range(NUM_LABELS)])

        _run_class_test(lambda: MultilabelAccuracy(NUM_LABELS, average=average), preds, target, ref)

    def test_f1(self):
        preds, target = _multilabel_data()

        def ref(p, t):
            p = (np.asarray(p) > 0.5).astype(int).reshape(-1, NUM_LABELS)
            t = np.asarray(t).reshape(-1, NUM_LABELS)
            return sk_f1_score(t, p, average="macro", zero_division=0)

        _run_class_test(lambda: MultilabelF1Score(NUM_LABELS, average="macro"), preds, target, ref)

    def test_confusion_matrix(self):
        preds, target = _multilabel_data()

        def ref(p, t):
            p = (np.asarray(p) > 0.5).astype(int).reshape(-1, NUM_LABELS)
            t = np.asarray(t).reshape(-1, NUM_LABELS)
            cms = sk_multilabel_confusion_matrix(t, p)
            return cms

        _run_class_test(lambda: MultilabelConfusionMatrix(NUM_LABELS), preds, target, ref)


# ------------------------------------------------------------------------------- jit


def test_update_is_jittable():
    """The whole format→update stage must lower to one XLA graph."""
    import jax

    @jax.jit
    def jitted(preds, target):
        from torchmetrics_tpu.functional.classification.stat_scores import (
            _multiclass_stat_scores_format,
            _multiclass_stat_scores_update,
        )

        p, t = _multiclass_stat_scores_format(preds, target, top_k=1)
        return _multiclass_stat_scores_update(p, t, NUM_CLASSES, 1, "macro", "global", None)

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.randn(64, NUM_CLASSES))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, 64))
    tp, fp, tn, fn = jitted(preds, target)
    labels = np.argmax(np.asarray(preds), axis=1)
    t = np.asarray(target)
    for c in range(NUM_CLASSES):
        assert int(tp[c]) == ((labels == c) & (t == c)).sum()


def test_mesh_sharded_update(mesh8):
    """Metric update on mesh-sharded batch + psum-style merge gives global result."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(1)
    preds = jnp.asarray(rng.randn(128, NUM_CLASSES))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, 128))

    from torchmetrics_tpu.functional.classification.stat_scores import (
        _multiclass_stat_scores_format,
        _multiclass_stat_scores_update,
    )

    def local_update(p, t):
        p, t = _multiclass_stat_scores_format(p, t, top_k=1)
        return jnp.stack(_multiclass_stat_scores_update(p, t, NUM_CLASSES, 1, "macro", "global", None))

    sharded_preds = jax.device_put(preds, NamedSharding(mesh8.mesh, P("data")))
    sharded_target = jax.device_put(target, NamedSharding(mesh8.mesh, P("data")))
    # global-array mode: XLA inserts collectives automatically for the full reduction
    stats = jax.jit(local_update)(sharded_preds, sharded_target)
    expected = local_update(preds, target)
    np.testing.assert_allclose(np.asarray(stats), np.asarray(expected))
