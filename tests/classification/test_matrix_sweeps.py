"""Reference-scale parametrization sweeps for the stat-scores family.

Models the reference's case matrices (``tests/unittests/classification/inputs.py:19-70``
and e.g. ``test_accuracy.py:38-65``): input kind (probs / logits / labels) x
ignore_index (None / -1) x average (micro/macro/weighted/none) x multidim_average
(global/samplewise), each checked against sklearn on the masked, host-formatted data.
Each family runs well over 20 parametrizations.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp
from sklearn.metrics import precision_recall_fscore_support as sk_prfs
from sklearn.metrics import multilabel_confusion_matrix as sk_mcm

from torchmetrics_tpu.classification import (
    BinaryAccuracy,
    BinaryF1Score,
    BinaryPrecision,
    BinaryRecall,
    BinarySpecificity,
    MulticlassAccuracy,
    MulticlassF1Score,
    MulticlassPrecision,
    MulticlassRecall,
    MulticlassSpecificity,
    MultilabelAccuracy,
    MultilabelF1Score,
    MultilabelPrecision,
    MultilabelRecall,
    MultilabelSpecificity,
)

NUM_CLASSES = 5
NUM_LABELS = 4
NUM_BATCHES = 4
BATCH_SIZE = 33  # deliberately not a multiple of anything
_RNG = np.random.RandomState(7)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _softmax(x, axis):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# ------------------------------------------------------------------ input cases

_binary_cases = {
    "probs": _RNG.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
    "logits": _RNG.randn(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
    "labels": _RNG.randint(0, 2, (NUM_BATCHES, BATCH_SIZE)),
}
_binary_target = _RNG.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))

_mc_cases = {
    "probs": _softmax(_RNG.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES), -1).astype(np.float32),
    "logits": _RNG.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32),
    "labels": _RNG.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
}
_mc_target = _RNG.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE))

_ml_cases = {
    "probs": _RNG.rand(NUM_BATCHES, BATCH_SIZE, NUM_LABELS).astype(np.float32),
    "logits": _RNG.randn(NUM_BATCHES, BATCH_SIZE, NUM_LABELS).astype(np.float32),
    "labels": _RNG.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_LABELS)),
}
_ml_target = _RNG.randint(0, 2, (NUM_BATCHES, BATCH_SIZE, NUM_LABELS))

# multidim (..., EXTRA) variants for samplewise sweeps
EXTRA = 6
_mc_md_preds = _RNG.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES, EXTRA).astype(np.float32)
_mc_md_target = _RNG.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE, EXTRA))


def _mask_ignore(labels, target, ignore_index):
    labels = np.asarray(labels).reshape(-1)
    target = np.asarray(target).reshape(-1)
    if ignore_index is None:
        return labels, target
    keep = target != ignore_index
    return labels[keep], target[keep]


def _inject_ignore(target, ignore_index, frac=0.1):
    if ignore_index is None:
        return target
    t = np.array(target)
    flat = t.reshape(-1)
    idx = _RNG.choice(flat.size, int(flat.size * frac), replace=False)
    flat[idx] = ignore_index
    return t


# ------------------------------------------------------------------ goldens


def _golden_prfs(labels, target, n_classes, average, beta=1.0):
    """precision/recall/f1 via sklearn; 'none' keeps per-class vectors."""
    avg = None if average in (None, "none") else average
    p, r, f, _ = sk_prfs(
        target, labels, labels=list(range(n_classes)), average=avg, beta=beta, zero_division=0
    )
    return p, r, f


def _golden_specificity(labels, target, n_classes, average):
    mcm = sk_mcm(target, labels, labels=list(range(n_classes)))
    tn, fp = mcm[:, 0, 0], mcm[:, 0, 1]
    fn, tp = mcm[:, 1, 0], mcm[:, 1, 1]
    if average == "micro":
        return tn.sum() / max(tn.sum() + fp.sum(), 1)
    per_class = np.where(tn + fp > 0, tn / np.maximum(tn + fp, 1), 0.0)
    if average == "macro":
        return per_class.mean()
    if average == "weighted":
        support = tp + fn
        return (per_class * support).sum() / max(support.sum(), 1)
    return per_class


def _golden_accuracy_multilabel(labels, target, average):
    """Reference multilabel accuracy: per-label (tp+tn)/(tp+tn+fp+fn)."""
    labels = labels.reshape(-1, NUM_LABELS)
    target = target.reshape(-1, NUM_LABELS)
    correct = (labels == target).astype(np.float64)
    if average == "micro":
        return correct.mean()
    per_label = correct.mean(axis=0)
    if average == "macro":
        return per_label.mean()
    if average == "weighted":
        support = target.sum(axis=0)
        return (per_label * support).sum() / max(support.sum(), 1)
    return per_label


# ------------------------------------------------------------------ binary sweep


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize(
    ("metric_cls", "golden_key"),
    [
        (BinaryAccuracy, "accuracy"),
        (BinaryPrecision, "precision"),
        (BinaryRecall, "recall"),
        (BinaryF1Score, "f1"),
        (BinarySpecificity, "specificity"),
    ],
)
def test_binary_sweep(kind, ignore_index, metric_cls, golden_key):
    preds = _binary_cases[kind]
    target = _inject_ignore(_binary_target, ignore_index)

    metric = metric_cls(ignore_index=ignore_index)
    for i in range(NUM_BATCHES):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    got = float(metric.compute())

    if kind == "labels":
        hard = preds
    else:
        p = _sigmoid(preds) if kind == "logits" else preds
        hard = (p > 0.5).astype(int)
    hard, masked_t = _mask_ignore(hard, target, ignore_index)
    if golden_key == "accuracy":
        want = float((hard == masked_t).mean())
    elif golden_key == "specificity":
        want = float(_golden_specificity(hard, masked_t, 2, None)[1])  # positive class
    else:
        p, r, f = _golden_prfs(hard, masked_t, 2, None)
        want = float({"precision": p, "recall": r, "f1": f}[golden_key][1])  # positive class
    np.testing.assert_allclose(got, want, atol=1e-6)


# ------------------------------------------------------------------ multiclass sweep


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
@pytest.mark.parametrize(
    ("metric_cls", "golden_key"),
    [
        (MulticlassPrecision, "precision"),
        (MulticlassRecall, "recall"),
        (MulticlassF1Score, "f1"),
        (MulticlassSpecificity, "specificity"),
    ],
)
def test_multiclass_sweep(kind, ignore_index, average, metric_cls, golden_key):
    preds = _mc_cases[kind]
    target = _inject_ignore(_mc_target, ignore_index)

    metric = metric_cls(num_classes=NUM_CLASSES, average=average, ignore_index=ignore_index)
    for i in range(NUM_BATCHES):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    got = np.asarray(metric.compute())

    hard = preds.argmax(-1) if kind != "labels" else preds
    hard, masked_t = _mask_ignore(hard, target, ignore_index)
    if golden_key == "specificity":
        want = _golden_specificity(hard, masked_t, NUM_CLASSES, average)
    else:
        p, r, f = _golden_prfs(hard, masked_t, NUM_CLASSES, average)
        want = {"precision": p, "recall": r, "f1": f}[golden_key]
    np.testing.assert_allclose(got, np.asarray(want, dtype=np.float64), atol=1e-6)


@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multiclass_accuracy_sweep(ignore_index, average):
    target = _inject_ignore(_mc_target, ignore_index)
    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average=average, ignore_index=ignore_index)
    for i in range(NUM_BATCHES):
        metric.update(jnp.asarray(_mc_cases["logits"][i]), jnp.asarray(target[i]))
    got = float(metric.compute())
    hard, masked_t = _mask_ignore(_mc_cases["logits"].argmax(-1), target, ignore_index)
    if average == "micro":
        want = float((hard == masked_t).mean())
    else:  # macro accuracy == macro recall
        _, r, _ = _golden_prfs(hard, masked_t, NUM_CLASSES, "macro")
        want = float(r)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("average", ["micro", "macro"])
def test_multiclass_samplewise_sweep(average):
    """multidim_average='samplewise': per-sample values over the EXTRA dim."""
    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average=average, multidim_average="samplewise")
    got = []
    for i in range(NUM_BATCHES):
        m = MulticlassAccuracy(num_classes=NUM_CLASSES, average=average, multidim_average="samplewise")
        m.update(jnp.asarray(_mc_md_preds[i]), jnp.asarray(_mc_md_target[i]))
        got.append(np.asarray(m.compute()))
    got = np.concatenate(got)

    hard = _mc_md_preds.argmax(2)  # (NB, B, EXTRA)
    want = []
    for i in range(NUM_BATCHES):
        for s in range(BATCH_SIZE):
            h, t = hard[i, s], _mc_md_target[i, s]
            if average == "micro":
                want.append((h == t).mean())
            else:
                # reference macro drops classes absent from preds AND target
                # (weights[tp+fp+fn == 0] = 0, utilities/compute.py:66-68)
                recalls = []
                for c in range(NUM_CLASSES):
                    support = (t == c).sum()
                    predicted = (h == c).sum()
                    if support + predicted == 0:
                        continue
                    recalls.append(((h == c) & (t == c)).sum() / max(support, 1))
                want.append(np.mean(recalls))
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)


# ------------------------------------------------------------------ multilabel sweep


@pytest.mark.parametrize("kind", ["probs", "logits", "labels"])
@pytest.mark.parametrize("ignore_index", [None, -1])
@pytest.mark.parametrize("average", ["micro", "macro", "none"])
@pytest.mark.parametrize(
    ("metric_cls", "golden_key"),
    [
        (MultilabelPrecision, "precision"),
        (MultilabelRecall, "recall"),
        (MultilabelF1Score, "f1"),
    ],
)
def test_multilabel_sweep(kind, ignore_index, average, metric_cls, golden_key):
    preds = _ml_cases[kind]
    target = _inject_ignore(_ml_target, ignore_index)

    metric = metric_cls(num_labels=NUM_LABELS, average=average, ignore_index=ignore_index)
    for i in range(NUM_BATCHES):
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    got = np.asarray(metric.compute())

    if kind == "labels":
        hard = preds
    else:
        p = _sigmoid(preds) if kind == "logits" else preds
        hard = (p > 0.5).astype(int)
    hard = hard.reshape(-1, NUM_LABELS)
    t = target.reshape(-1, NUM_LABELS)
    # per-label tp/fp/fn with ignore_index masking
    tps, fps, fns = [], [], []
    for lab in range(NUM_LABELS):
        h, tt = hard[:, lab], t[:, lab]
        if ignore_index is not None:
            keep = tt != ignore_index
            h, tt = h[keep], tt[keep]
        tps.append(((h == 1) & (tt == 1)).sum())
        fps.append(((h == 1) & (tt == 0)).sum())
        fns.append(((h == 0) & (tt == 1)).sum())
    tp, fp, fn = np.asarray(tps, float), np.asarray(fps, float), np.asarray(fns, float)
    if golden_key == "precision":
        per = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 0.0)
    elif golden_key == "recall":
        per = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), 0.0)
    else:
        per = np.where(2 * tp + fp + fn > 0, 2 * tp / np.maximum(2 * tp + fp + fn, 1), 0.0)
    if average == "micro":
        s_tp, s_fp, s_fn = tp.sum(), fp.sum(), fn.sum()
        if golden_key == "precision":
            want = s_tp / max(s_tp + s_fp, 1)
        elif golden_key == "recall":
            want = s_tp / max(s_tp + s_fn, 1)
        else:
            want = 2 * s_tp / max(2 * s_tp + s_fp + s_fn, 1)
    elif average == "macro":
        want = per.mean()
    else:
        want = per
    np.testing.assert_allclose(got, np.asarray(want, dtype=np.float64), atol=1e-6)


@pytest.mark.parametrize("average", ["micro", "macro", "weighted", "none"])
def test_multilabel_accuracy_sweep(average):
    metric = MultilabelAccuracy(num_labels=NUM_LABELS, average=average)
    for i in range(NUM_BATCHES):
        metric.update(jnp.asarray(_ml_cases["probs"][i]), jnp.asarray(_ml_target[i]))
    got = np.asarray(metric.compute())
    hard = (_ml_cases["probs"] > 0.5).astype(int)
    want = _golden_accuracy_multilabel(hard, _ml_target, average)
    np.testing.assert_allclose(got, np.asarray(want, dtype=np.float64), atol=1e-6)
