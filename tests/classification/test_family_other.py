"""Sweeps for the remaining classification families: calibration error, hinge,
exact match, dice, curves (multiclass/multilabel ROC & PR), and ranking.

Goldens are hand-rolled numpy implementations of the reference definitions
(``functional/classification/{calibration_error,hinge,exact_match,ranking}.py``)
plus sklearn for the curve point sets.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp
from sklearn.metrics import precision_recall_curve as sk_prc
from sklearn.metrics import roc_curve as sk_roc

from torchmetrics_tpu.classification import (
    BinaryCalibrationError,
    BinaryHingeLoss,
    Dice,
    MulticlassCalibrationError,
    MulticlassExactMatch,
    MulticlassHingeLoss,
    MulticlassPrecisionRecallCurve,
    MulticlassROC,
    MultilabelCoverageError,
    MultilabelExactMatch,
    MultilabelPrecisionRecallCurve,
    MultilabelRankingAveragePrecision,
    MultilabelRankingLoss,
    MultilabelROC,
)

NC = 5
NL = 4
N = 170
_RNG = np.random.RandomState(43)


def _softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


_bin_probs = _RNG.rand(N).astype(np.float64)
_bin_target = _RNG.randint(0, 2, N)
_mc_probs = _softmax(_RNG.randn(N, NC)).astype(np.float64)
_mc_target = _RNG.randint(0, NC, N)
_ml_probs = _RNG.rand(N, NL).astype(np.float64)
_ml_target = _RNG.randint(0, 2, (N, NL))


# ------------------------------------------------------------------ calibration error


def _golden_ece(confidences, accuracies, n_bins, norm):
    """Reference binning: uniform bins over [0, 1], boundary by torch.bucketize
    semantics (``calibration_error.py _binning_bucketize``)."""
    bins = np.linspace(0, 1, n_bins + 1)
    idx = np.digitize(confidences, bins[1:-1], right=False)
    ece_terms = []
    for b in range(n_bins):
        sel = idx == b
        if not sel.any():
            continue
        prop = sel.mean()
        conf = confidences[sel].mean()
        acc = accuracies[sel].mean()
        ece_terms.append((abs(acc - conf), prop))
    if norm == "l1":
        return sum(d * p for d, p in ece_terms)
    if norm == "max":
        return max(d for d, _ in ece_terms)
    return np.sqrt(sum(d * d * p for d, p in ece_terms))


@pytest.mark.parametrize("n_bins", [10, 15, 30])
@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
def test_binary_calibration_error_matrix(n_bins, norm):
    m = BinaryCalibrationError(n_bins=n_bins, norm=norm)
    m.update(jnp.asarray(_bin_probs), jnp.asarray(_bin_target))
    got = float(m.compute())
    # reference binary semantics: confidence = p(positive), accuracy = target
    # (calibration_error.py:134-136) — NOT top-label confidence/correctness; the
    # two agree under l1/l2 by mirror symmetry but differ for max
    want = _golden_ece(_bin_probs, _bin_target.astype(float), n_bins, norm)
    np.testing.assert_allclose(got, want, atol=1e-6)


@pytest.mark.parametrize("n_bins", [10, 20])
@pytest.mark.parametrize("norm", ["l1", "max"])
def test_multiclass_calibration_error_matrix(n_bins, norm):
    m = MulticlassCalibrationError(num_classes=NC, n_bins=n_bins, norm=norm)
    m.update(jnp.asarray(_mc_probs), jnp.asarray(_mc_target))
    got = float(m.compute())
    conf = _mc_probs.max(-1)
    acc = (_mc_probs.argmax(-1) == _mc_target).astype(float)
    want = _golden_ece(conf, acc, n_bins, norm)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_perfectly_calibrated_one_hot_is_zero():
    onehot = np.eye(NC)[_mc_target]
    m = MulticlassCalibrationError(num_classes=NC, n_bins=10, norm="l1")
    m.update(jnp.asarray(onehot), jnp.asarray(_mc_target))
    np.testing.assert_allclose(float(m.compute()), 0.0, atol=1e-6)


# ------------------------------------------------------------------ hinge


@pytest.mark.parametrize("squared", [False, True])
def test_binary_hinge_matrix(squared):
    """Reference formats logits through sigmoid first (confusion_matrix format with
    convert_to_labels=False); the margin is computed on the PROBABILITY."""
    logits = _RNG.randn(N)
    m = BinaryHingeLoss(squared=squared)
    m.update(jnp.asarray(logits), jnp.asarray(_bin_target))
    got = float(m.compute())
    p = 1.0 / (1.0 + np.exp(-logits))
    margin = np.where(_bin_target == 1, p, -p)
    measures = np.maximum(1 - margin, 0.0)
    want = (measures**2 if squared else measures).mean()
    np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("multiclass_mode", ["crammer-singer", "one-vs-all"])
def test_multiclass_hinge_matrix(multiclass_mode):
    """Logits are softmaxed by the format stage; margins computed on probabilities."""
    logits = _RNG.randn(N, NC)
    m = MulticlassHingeLoss(num_classes=NC, multiclass_mode=multiclass_mode)
    m.update(jnp.asarray(logits), jnp.asarray(_mc_target))
    got = np.asarray(m.compute())
    probs = _softmax(logits)
    if multiclass_mode == "crammer-singer":
        true_score = probs[np.arange(N), _mc_target]
        masked = probs.copy()
        masked[np.arange(N), _mc_target] = -np.inf
        best_other = masked.max(-1)
        want = np.maximum(1 - (true_score - best_other), 0).mean()
    else:  # reference one-vs-all returns a per-class vector
        t = np.full((N, NC), -1.0)
        t[np.arange(N), _mc_target] = 1.0
        want = np.maximum(1 - t * probs, 0).mean(0)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-6)


# ------------------------------------------------------------------ exact match


@pytest.mark.parametrize("multidim_average", ["global", "samplewise"])
def test_multiclass_exact_match_matrix(multidim_average):
    extra = 7
    preds = _RNG.randint(0, NC, (N, extra))
    target = preds.copy()
    flip = _RNG.rand(N, extra) < 0.3
    target[flip] = _RNG.randint(0, NC, flip.sum())
    m = MulticlassExactMatch(num_classes=NC, multidim_average=multidim_average)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    got = np.asarray(m.compute())
    rows_equal = (preds == target).all(axis=1)
    want = rows_equal.astype(float) if multidim_average == "samplewise" else rows_equal.mean()
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-6)


def test_multilabel_exact_match_matrix():
    m = MultilabelExactMatch(num_labels=NL)
    m.update(jnp.asarray(_ml_probs), jnp.asarray(_ml_target))
    got = float(m.compute())
    hard = (_ml_probs > 0.5).astype(int)
    want = (hard == _ml_target).all(axis=1).mean()
    np.testing.assert_allclose(got, want, atol=1e-6)


# ------------------------------------------------------------------ dice


@pytest.mark.parametrize("average", ["micro", "macro"])
@pytest.mark.parametrize("ignore_index", [None, 0])
def test_dice_matrix(average, ignore_index):
    preds = _RNG.randint(0, NC, N)
    target = _RNG.randint(0, NC, N)
    m = Dice(num_classes=NC, average=average, ignore_index=ignore_index)
    m.update(jnp.asarray(preds), jnp.asarray(target))
    got = float(m.compute())

    classes = [c for c in range(NC) if c != ignore_index]
    tp = np.asarray([((preds == c) & (target == c)).sum() for c in classes], float)
    fp = np.asarray([((preds == c) & (target != c)).sum() for c in classes], float)
    fn = np.asarray([((preds != c) & (target == c)).sum() for c in classes], float)
    if average == "micro":
        want = 2 * tp.sum() / max(2 * tp.sum() + fp.sum() + fn.sum(), 1)
    else:
        per = np.where(2 * tp + fp + fn > 0, 2 * tp / np.maximum(2 * tp + fp + fn, 1), np.nan)
        want = np.nanmean(per)
    np.testing.assert_allclose(got, want, atol=1e-6)


# ------------------------------------------------------------------ curves (exact)


def test_multiclass_roc_points_vs_sklearn():
    m = MulticlassROC(num_classes=NC, thresholds=None)
    m.update(jnp.asarray(_mc_probs), jnp.asarray(_mc_target))
    fprs, tprs, _ = m.compute()
    for c in range(NC):
        # drop_intermediate=False keeps every threshold, like the reference's exact
        # curve; point sets must then agree up to the (0, 0) endpoint convention
        fpr_sk, tpr_sk, _ = sk_roc((_mc_target == c).astype(int), _mc_probs[:, c], drop_intermediate=False)
        got = set(zip(np.round(np.asarray(fprs[c]), 6), np.round(np.asarray(tprs[c]), 6)))
        want = set(zip(np.round(fpr_sk, 6), np.round(tpr_sk, 6)))
        assert want <= got, f"class {c}: missing {sorted(want - got)[:4]}"
        assert got <= want | {(0.0, 0.0)}, f"class {c}: spurious {sorted(got - want)[:4]}"


def test_multilabel_pr_curve_points_vs_sklearn():
    m = MultilabelPrecisionRecallCurve(num_labels=NL, thresholds=None)
    m.update(jnp.asarray(_ml_probs), jnp.asarray(_ml_target))
    precs, recs, _ = m.compute()
    for c in range(NL):
        p_sk, r_sk, _ = sk_prc(_ml_target[:, c], _ml_probs[:, c])
        got = set(zip(np.round(np.asarray(precs[c]), 6), np.round(np.asarray(recs[c]), 6)))
        want = set(zip(np.round(p_sk, 6), np.round(r_sk, 6)))
        assert len(want - got) <= 1, f"label {c}: {sorted(want - got)[:4]}"


@pytest.mark.parametrize("n_thresholds", [20, 100])
def test_binned_curves_converge_to_exact(n_thresholds):
    """Binned AUROC approaches the exact value as thresholds densify."""
    from torchmetrics_tpu.classification import MulticlassAUROC

    exact = MulticlassAUROC(num_classes=NC, thresholds=None)
    exact.update(jnp.asarray(_mc_probs), jnp.asarray(_mc_target))
    binned = MulticlassAUROC(num_classes=NC, thresholds=n_thresholds)
    binned.update(jnp.asarray(_mc_probs), jnp.asarray(_mc_target))
    tol = 0.05 if n_thresholds == 20 else 0.01
    np.testing.assert_allclose(float(binned.compute()), float(exact.compute()), atol=tol)


def test_multilabel_auroc_binned_equals_exact_on_own_scores():
    """Thresholds taken from the observed score values: binned AUROC == exact
    (same floats on both sides, so no grid-quantisation slack)."""
    from torchmetrics_tpu.classification import MultilabelAUROC

    scores = np.round(_ml_probs * 20) / 20 * 0.9 + 0.05  # keep strictly inside (0, 1)
    # the grid needs one threshold above every score so the binned curve reaches
    # (0, 0) like the exact one (whose implicit top threshold is +inf)
    thresholds = jnp.asarray(np.concatenate([np.unique(scores), [1.0]]))
    exact = MultilabelAUROC(num_labels=NL, average="macro", thresholds=None)
    exact.update(jnp.asarray(scores), jnp.asarray(_ml_target))
    binned = MultilabelAUROC(num_labels=NL, average="macro", thresholds=thresholds)
    binned.update(jnp.asarray(scores), jnp.asarray(_ml_target))
    np.testing.assert_allclose(float(binned.compute()), float(exact.compute()), atol=1e-6)


# ------------------------------------------------------------------ ranking


def test_ranking_metrics_vs_sklearn():
    from sklearn.metrics import coverage_error as sk_cov
    from sklearn.metrics import label_ranking_average_precision_score as sk_lrap
    from sklearn.metrics import label_ranking_loss as sk_rloss

    for cls, sk_fn in [
        (MultilabelCoverageError, sk_cov),
        (MultilabelRankingAveragePrecision, sk_lrap),
        (MultilabelRankingLoss, sk_rloss),
    ]:
        m = cls(num_labels=NL)
        for chunk_p, chunk_t in zip(np.array_split(_ml_probs, 3), np.array_split(_ml_target, 3)):
            m.update(jnp.asarray(chunk_p), jnp.asarray(chunk_t))
        got = float(m.compute())
        want = sk_fn(_ml_target, _ml_probs)
        np.testing.assert_allclose(got, want, rtol=1e-6, err_msg=cls.__name__)
