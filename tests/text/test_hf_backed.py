"""End-to-end tests for the HF-backed default paths of BERTScore / InfoLM / CLIPScore.

No egress in CI: a tiny Flax BERT checkpoint + WordPiece vocab are written with
``save_pretrained`` to a tmp dir and loaded back through the exact code path a user's
``model_name_or_path`` takes (reference ``text/bert.py:192-195``). Hub ids that are
not cached must fail with the actionable offline error, not an HTTP traceback.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax.numpy as jnp

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from torchmetrics_tpu.functional.text.bert import bert_score  # noqa: E402
from torchmetrics_tpu.functional.text.infolm import infolm  # noqa: E402
from torchmetrics_tpu.text.bert import BERTScore  # noqa: E402

_VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "hello", "world", "the", "cat", "sat", "on", "mat", "a", "dog", "ran", "in", "park",
]


def _tiny_bert_config():
    """One config shared by the flax- and torch-weight fixtures: the torch-vs-flax
    comparison only means something if both checkpoints have the same shape."""
    return transformers.BertConfig(
        vocab_size=len(_VOCAB), hidden_size=16, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=32, max_position_embeddings=64,
    )


@pytest.fixture(scope="module")
def tiny_bert_dir(tmp_path_factory):
    """A local save_pretrained checkpoint: tiny FlaxBertForMaskedLM + matching tokenizer."""
    d = tmp_path_factory.mktemp("tiny_bert")
    vocab = d / "vocab.txt"
    vocab.write_text("\n".join(_VOCAB))
    tok = transformers.BertTokenizer(str(vocab))
    tok.save_pretrained(str(d))
    model = transformers.FlaxBertForMaskedLM(_tiny_bert_config(), seed=0)
    model.save_pretrained(str(d))
    return str(d)


def test_bert_score_from_local_checkpoint(tiny_bert_dir):
    """model_name_or_path drives tokenizer + Flax model end-to-end; identical
    sentences score 1.0 and different sentences score strictly lower."""
    preds = ["hello world", "the cat sat on the mat"]
    target = ["hello world", "the cat sat on the mat"]
    out = bert_score(preds, target, model_name_or_path=tiny_bert_dir, max_length=16)
    np.testing.assert_allclose(np.asarray(out["f1"]), 1.0, atol=1e-4)

    out2 = bert_score(["a dog ran in the park"], ["the cat sat on the mat"],
                      model_name_or_path=tiny_bert_dir, max_length=16)
    assert float(np.asarray(out2["f1"])[0]) < 1.0 - 1e-4


def test_bert_score_modular_with_idf(tiny_bert_dir):
    metric = BERTScore(model_name_or_path=tiny_bert_dir, idf=True, max_length=16)
    metric.update(["hello world"], ["hello world"])
    metric.update(["the cat sat"], ["the cat sat"])
    out = metric.compute()
    np.testing.assert_allclose(np.asarray(out["f1"]), 1.0, atol=1e-4)


def test_bert_score_num_layers(tiny_bert_dir):
    out = bert_score(["hello world"], ["hello world"],
                     model_name_or_path=tiny_bert_dir, num_layers=1, max_length=16)
    np.testing.assert_allclose(np.asarray(out["f1"]), 1.0, atol=1e-4)


def test_infolm_from_local_checkpoint(tiny_bert_dir):
    """Masked-LM distribution pipeline: identical corpora give ~0 divergence."""
    score = infolm(["hello world"], ["hello world"], model_name_or_path=tiny_bert_dir, idf=False)
    np.testing.assert_allclose(float(score), 0.0, atol=1e-4)
    score2 = infolm(["a dog ran in the park"], ["the cat sat on the mat"],
                    model_name_or_path=tiny_bert_dir, idf=False)
    assert float(score2) > float(score)


def _raise_not_cached(*args, **kwargs):
    raise OSError("no cached snapshot found (simulated offline hub)")


def test_uncached_hub_id_fails_cleanly(monkeypatch):
    """An uncached hub id surfaces the actionable offline error, not a hub traceback.

    The hub failure is simulated by patching ``from_pretrained`` — env-var switches
    like HF_HUB_OFFLINE are read at transformers import time, so setting them here
    would be a no-op on a machine with network access.
    """
    from torchmetrics_tpu.utilities import hf as hf_utils

    hf_utils.load_hf_model_and_tokenizer.cache_clear()
    monkeypatch.setattr(transformers.AutoTokenizer, "from_pretrained", _raise_not_cached)
    monkeypatch.setattr(transformers.FlaxAutoModel, "from_pretrained", _raise_not_cached)
    monkeypatch.setattr(transformers.AutoModel, "from_pretrained", _raise_not_cached)
    with pytest.raises(ModuleNotFoundError, match="cached"):
        bert_score(["x"], ["x"], model_name_or_path="no-such-org/no-such-model")


def test_clip_score_uncached_fails_cleanly(monkeypatch):
    from torchmetrics_tpu.functional.multimodal.clip_score import clip_score

    monkeypatch.setattr(transformers.CLIPModel, "from_pretrained", _raise_not_cached)
    monkeypatch.setattr(transformers.CLIPProcessor, "from_pretrained", _raise_not_cached)
    with pytest.raises(ModuleNotFoundError, match="cached"):
        clip_score(jnp.zeros((3, 32, 32), dtype=jnp.uint8), "a photo")


@pytest.fixture(scope="module")
def tiny_clip_dir(tmp_path_factory):
    """A local save_pretrained CLIP checkpoint: tiny towers + tokenizer + processor."""
    import json

    d = tmp_path_factory.mktemp("tiny_clip")
    vocab = {"<|startoftext|>": 0, "<|endoftext|>": 1, "a</w>": 2, "photo</w>": 3,
             "of</w>": 4, "cat</w>": 5, "dog</w>": 6}
    json.dump(vocab, open(d / "vocab.json", "w"))
    (d / "merges.txt").write_text("#version: 0.2\n")
    tok = transformers.CLIPTokenizer(str(d / "vocab.json"), str(d / "merges.txt"))
    tok.save_pretrained(str(d))
    config = transformers.CLIPConfig(
        text_config={"vocab_size": len(vocab), "hidden_size": 16, "num_hidden_layers": 2,
                     "num_attention_heads": 2, "intermediate_size": 32,
                     "max_position_embeddings": 16, "projection_dim": 8},
        vision_config={"hidden_size": 16, "num_hidden_layers": 2, "num_attention_heads": 2,
                       "intermediate_size": 32, "image_size": 32, "patch_size": 8,
                       "projection_dim": 8},
        projection_dim=8,
    )
    torch_model = transformers.CLIPModel(config)
    torch_model.eval()
    torch_model.save_pretrained(str(d))
    transformers.CLIPImageProcessor(
        size={"shortest_edge": 32}, crop_size={"height": 32, "width": 32}
    ).save_pretrained(str(d))
    return str(d)


def test_clip_score_from_local_checkpoint(tiny_clip_dir):
    """model_name_or_path drives the full HF CLIP path end-to-end, offline."""
    from torchmetrics_tpu.multimodal import CLIPScore

    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, size=(2, 3, 32, 32), dtype=np.uint8))
    metric = CLIPScore(model_name_or_path=tiny_clip_dir)
    metric.update(imgs, ["a photo of a cat", "a photo of a dog"])
    val = float(metric.compute())
    assert np.isfinite(val) and 0.0 <= val <= 100.0

    # per-pair scores are deterministic for a fixed checkpoint
    from torchmetrics_tpu.functional.multimodal import clip_score

    v1 = float(clip_score(imgs, ["a photo of a cat", "a photo of a dog"], model_name_or_path=tiny_clip_dir))
    v2 = float(clip_score(imgs, ["a photo of a cat", "a photo of a dog"], model_name_or_path=tiny_clip_dir))
    assert v1 == v2


def test_rouge_compute_handles_synced_array_state():
    """After a distributed sync, cat-reduced states arrive as one array of per-sample
    scores; compute must return the scalar mean (reference averages unconditionally)."""
    from torchmetrics_tpu.functional.text.rouge import _rouge_score_compute

    out = _rouge_score_compute({"rouge1_fmeasure": jnp.asarray([0.2, 0.4, 0.6])})
    assert np.asarray(out["rouge1_fmeasure"]).shape == ()
    np.testing.assert_allclose(float(out["rouge1_fmeasure"]), 0.4, atol=1e-6)

    out = _rouge_score_compute({"rouge1_fmeasure": [0.25, jnp.asarray([0.5, 0.75])]})
    np.testing.assert_allclose(float(out["rouge1_fmeasure"]), 0.5, atol=1e-6)


@pytest.fixture(scope="module")
def tiny_bert_pt_dir(tmp_path_factory):
    """The same tiny config saved as TORCH weights only — exercises the from_pt
    conversion branch of load_hf_flax_model."""
    d = tmp_path_factory.mktemp("tiny_bert_pt")
    vocab = d / "vocab.txt"
    vocab.write_text("\n".join(_VOCAB))
    transformers.BertTokenizer(str(vocab)).save_pretrained(str(d))
    config = transformers.BertConfig(
        vocab_size=len(_VOCAB), hidden_size=16, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=32, max_position_embeddings=64,
    )
    torch.manual_seed(0)
    # .bin (not safetensors): flax load must FAIL first, driving the from_pt retry
    transformers.BertForMaskedLM(config).save_pretrained(str(d), safe_serialization=False)
    return str(d)


def test_flax_load_matches_torch_forward(tiny_bert_pt_dir):
    """Numeric proof for the Flax-first text path: loading torch weights through
    load_hf_flax_model (from_pt conversion) produces hidden states equal to the
    torch model's own forward — the feature tensors BERTScore consumes."""
    from torchmetrics_tpu.utilities.hf import hf_embedding_forward, load_hf_flax_model, load_hf_tokenizer

    model = load_hf_flax_model(tiny_bert_pt_dir)
    assert getattr(model, "framework", None) == "flax"  # conversion path, not torch fallback
    tok = load_hf_tokenizer(tiny_bert_pt_dir)
    enc = tok(["hello world", "the cat sat on the mat"], padding="max_length",
              max_length=16, truncation=True, return_tensors="np")

    forward = hf_embedding_forward(model, num_layers=2)
    got = np.asarray(forward(enc["input_ids"], enc["attention_mask"]))

    tmodel = transformers.BertForMaskedLM.from_pretrained(tiny_bert_pt_dir)
    tmodel.eval()
    with torch.no_grad():
        out = tmodel(
            input_ids=torch.as_tensor(np.asarray(enc["input_ids"])),
            attention_mask=torch.as_tensor(np.asarray(enc["attention_mask"])),
            output_hidden_states=True,
        )
    want = out.hidden_states[2].numpy()
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_bert_score_tokenized_states_ride_array_sync(tiny_bert_dir):
    """With a tokenizer available, update() stores padded token ARRAYS (not strings);
    a pluggable world-2 sync duplicates them and compute scores the doubled corpus —
    the cross-process semantics raw string states cannot have."""
    metric = BERTScore(
        model_name_or_path=tiny_bert_dir, max_length=16, idf=True,
        dist_sync_fn=lambda x, group=None: [x, x],
        distributed_available_fn=lambda: True,
    )
    metric.update(["hello world", "the cat sat"], ["hello world", "a cat sat"])
    assert len(metric.preds) == 0  # no string fallback used
    assert len(metric.pred_input_ids) == 1 and metric.pred_input_ids[0].shape[0] == 2
    out = metric.compute()
    f1 = np.asarray(out["f1"])
    assert f1.shape == (4,)  # doubled world
    np.testing.assert_allclose(f1[:2], f1[2:], atol=1e-6)  # same pairs, same scores

    # pickle round-trip drops the resolved HF closures and re-resolves lazily
    # (pickled WITHOUT the unpicklable lambda sync hooks of the metric above)
    import pickle

    plain = BERTScore(model_name_or_path=tiny_bert_dir, max_length=16)
    plain.update(["hello world"], ["hello world"])
    clone = pickle.loads(pickle.dumps(plain))
    assert clone._resolved is False
    np.testing.assert_allclose(np.asarray(clone.compute()["f1"]), 1.0, atol=1e-4)


def test_bert_score_dynamic_width_tokenizer_normalized():
    """A user tokenizer that pads per-batch ('longest') still yields cat-able
    fixed-width states; zero padding is score-neutral (mask-weighted)."""
    import jax.numpy as jnp

    D = 5

    def tok(sents):
        width = max(len(s.split()) for s in sents) + 2  # dynamic per batch
        ids = np.zeros((len(sents), width), np.int32)
        mask = np.zeros((len(sents), width), np.int32)
        for i, s in enumerate(sents):
            t = [1] + [sum(map(ord, w)) % 97 + 3 for w in s.split()] + [2]
            ids[i, : len(t)] = t
            mask[i, : len(t)] = 1
        return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}

    def fwd(ids, mask):
        return jnp.sin(jnp.asarray(ids, jnp.float32)[:, :, None] * (np.arange(1, D + 1) * 0.3))

    m = BERTScore(model=fwd, user_tokenizer=tok, max_length=12)
    m.update(["short one"], ["short one"])
    m.update(["a much longer sentence with many words"], ["a much longer sentence with many words"])
    out = m.compute()  # widths 4 and 9, normalized to 12
    np.testing.assert_allclose(np.asarray(out["f1"]), 1.0, atol=1e-5)

    over = BERTScore(model=fwd, user_tokenizer=tok, max_length=4)
    with pytest.raises(ValueError, match="max_length"):
        over.update(["this sentence is far too long for four"], ["x"] )


def test_mixed_empty_cat_state_sync_raises(monkeypatch):
    """One populated rank + one empty rank: the count pre-gather fails loud on the
    would-deadlock configuration; all-empty stays a benign consistent skip."""
    import jax
    from jax.experimental import multihost_utils
    from torchmetrics_tpu.aggregation import CatMetric
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x, tiled=False: np.asarray([[0], [1]]))  # (world, n_cat_states)

    m = CatMetric(dist_sync_fn=lambda x, group=None: [x, x],
                  distributed_available_fn=lambda: True)
    with pytest.raises(TorchMetricsUserError, match="deadlock"):
        m._sync_dist(dist_sync_fn=m.dist_sync_fn)

    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x, tiled=False: np.asarray([[0], [0]]))
    m._sync_dist(dist_sync_fn=m.dist_sync_fn)  # all-empty: consistent no-op
    assert m.value == []


def test_infolm_tokenized_states_and_sync(tiny_bert_dir):
    """InfoLM stores fixed-width token arrays (not strings) on the HF path; a
    pluggable world-2 sync doubles the corpus and the mean score is unchanged
    (same pairs twice); matches the functional API on the same inputs."""
    from torchmetrics_tpu.text import InfoLM
    from torchmetrics_tpu.functional.text.infolm import infolm

    preds = ["hello world", "the cat sat"]
    target = ["hello world", "a cat sat"]
    m = InfoLM(model_name_or_path=tiny_bert_dir, idf=False,
               dist_sync_fn=lambda x, group=None: [x, x],
               distributed_available_fn=lambda: True)
    m.update(preds, target)
    assert len(m.preds) == 0 and len(m.pred_input_ids) == 1
    synced = float(m.compute())
    want = float(infolm(preds, target, model_name_or_path=tiny_bert_dir, idf=False))
    np.testing.assert_allclose(synced, want, atol=1e-6)  # near-zero KL: padding-width float noise ~1e-8

    import pickle

    plain = InfoLM(model_name_or_path=tiny_bert_dir, idf=True)
    plain.update(preds, target)
    clone = pickle.loads(pickle.dumps(plain))
    assert clone._resolved is False
    assert np.isfinite(float(clone.compute()))
