"""Reference-scale sweeps for the tensor-free text metrics.

Goldens: hand-rolled Levenshtein for the WER family (the reference defers to the
same dynamic program), nltk for BLEU, and degenerate-input policies (empty /
identical / disjoint pairs) across every string metric, mirroring the reference's
``tests/unittests/text/*`` case grids.
"""

from __future__ import annotations

import numpy as np
import pytest

from torchmetrics_tpu.functional.text import (
    bleu_score,
    char_error_rate,
    chrf_score,
    extended_edit_distance,
    match_error_rate,
    rouge_score,
    sacre_bleu_score,
    squad,
    translation_edit_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from torchmetrics_tpu.text import CharErrorRate, MatchErrorRate, WordErrorRate

_PREDS = [
    "the quick brown fox jumps over the lazy dog",
    "hello world",
    "a completely different sentence here",
    "one more example for the suite",
]
_TARGET = [
    "the quick brown fox jumped over a lazy dog",
    "hello there world",
    "nothing matches this reference at all",
    "one more example for the suite",
]


def _levenshtein(a, b):
    """(edits, len_b) via the standard DP — the WER-family spec."""
    m, n = len(a), len(b)
    d = np.zeros((m + 1, n + 1), dtype=int)
    d[:, 0] = np.arange(m + 1)
    d[0, :] = np.arange(n + 1)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1, d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[m, n]


def _wer_golden(preds, target, tokens=str.split):
    errs = sum(_levenshtein(tokens(p), tokens(t)) for p, t in zip(preds, target))
    total = sum(len(tokens(t)) for t in target)
    return errs / total


# ------------------------------------------------------------------ WER family


def test_wer_vs_levenshtein_golden():
    got = float(word_error_rate(_PREDS, _TARGET))
    np.testing.assert_allclose(got, _wer_golden(_PREDS, _TARGET), atol=1e-6)


def test_cer_vs_levenshtein_golden():
    got = float(char_error_rate(_PREDS, _TARGET))
    np.testing.assert_allclose(got, _wer_golden(_PREDS, _TARGET, tokens=list), atol=1e-6)


def test_mer_golden():
    """MER = S+D+I over S+D+I+H per the reference's accumulation."""
    errs, denom = 0, 0
    for p, t in zip(_PREDS, _TARGET):
        pw, tw = p.split(), t.split()
        e = _levenshtein(pw, tw)
        # hits via DP-free identity: H = (len_p + len_t - (S + 2*(D... use alignment:
        # MER denominator = errors + hits; hits = len_t - (deletions + substitutions).
        # With plain Levenshtein counts: H >= len_t - e, equality when no insertions
        # counted against hits; reference uses the aligned counts, so recompute DP
        # with operation tracking instead:
        m, n = len(pw), len(tw)
        d = np.zeros((m + 1, n + 1), dtype=int)
        d[:, 0] = np.arange(m + 1)
        d[0, :] = np.arange(n + 1)
        for i in range(1, m + 1):
            for j in range(1, n + 1):
                d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1, d[i - 1, j - 1] + (pw[i - 1] != tw[j - 1]))
        # backtrack for hits
        i, j, hits = m, n, 0
        while i > 0 and j > 0:
            if pw[i - 1] == tw[j - 1] and d[i, j] == d[i - 1, j - 1]:
                hits += 1
                i, j = i - 1, j - 1
            elif d[i, j] == d[i - 1, j - 1] + 1:
                i, j = i - 1, j - 1
            elif d[i, j] == d[i - 1, j] + 1:
                i -= 1
            else:
                j -= 1
        errs += e
        denom += e + hits
    got = float(match_error_rate(_PREDS, _TARGET))
    np.testing.assert_allclose(got, errs / denom, atol=1e-6)


def test_wip_wil_complementarity():
    wip = float(word_information_preserved(_PREDS, _TARGET))
    wil = float(word_information_lost(_PREDS, _TARGET))
    np.testing.assert_allclose(wip + wil, 1.0, atol=1e-6)
    assert 0.0 <= wip <= 1.0


@pytest.mark.parametrize(
    ("metric", "cls"),
    [(word_error_rate, WordErrorRate), (char_error_rate, CharErrorRate), (match_error_rate, MatchErrorRate)],
)
def test_modular_accumulation_equals_functional(metric, cls):
    m = cls()
    for p, t in zip(_PREDS, _TARGET):
        m.update([p], [t])
    np.testing.assert_allclose(float(m.compute()), float(metric(_PREDS, _TARGET)), atol=1e-6)


@pytest.mark.parametrize(
    "fn", [word_error_rate, char_error_rate, match_error_rate, word_information_lost]
)
def test_identical_pairs_are_zero(fn):
    np.testing.assert_allclose(float(fn(_TARGET, _TARGET)), 0.0, atol=1e-7)


def test_empty_prediction_is_all_deletions():
    np.testing.assert_allclose(float(word_error_rate([""], ["three word target"])), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(char_error_rate([""], ["abc"])), 1.0, atol=1e-6)


# ------------------------------------------------------------------ BLEU vs nltk


def _hand_corpus_bleu(preds, targets, n_max):
    """Papineni corpus BLEU from first principles: clipped n-gram counts, geometric
    mean, brevity penalty. (nltk's corpus_bleu deviates slightly at n>=3 when some
    hypotheses have fewer than n words, so the paper formula is the golden.)"""
    from collections import Counter

    log_p = []
    c = sum(len(p.split()) for p in preds)
    r = sum(len(t.split()) for t in targets)
    for n in range(1, n_max + 1):
        num = den = 0
        for p, t in zip(preds, targets):
            pw, tw = p.split(), t.split()
            pc = Counter(tuple(pw[i : i + n]) for i in range(len(pw) - n + 1))
            tc = Counter(tuple(tw[i : i + n]) for i in range(len(tw) - n + 1))
            num += sum(min(v, tc[k]) for k, v in pc.items())
            den += max(len(pw) - n + 1, 0)
        log_p.append(np.log(num / den) if num > 0 else -np.inf)
    bp = 1.0 if c > r else np.exp(1 - r / c)
    return bp * np.exp(np.mean(log_p))


@pytest.mark.parametrize("n_gram", [1, 2, 3, 4])
def test_bleu_vs_paper_formula(n_gram):
    want = _hand_corpus_bleu(_PREDS, _TARGET, n_gram)
    got = float(bleu_score(_PREDS, [[t] for t in _TARGET], n_gram=n_gram))
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("n_gram", [1, 2])
def test_bleu_vs_nltk_where_implementations_agree(n_gram):
    nltk_bleu = pytest.importorskip("nltk.translate.bleu_score")
    weights = tuple(1.0 / n_gram for _ in range(n_gram))
    want = nltk_bleu.corpus_bleu([[t.split()] for t in _TARGET], [p.split() for p in _PREDS], weights=weights)
    got = float(bleu_score(_PREDS, [[t] for t in _TARGET], n_gram=n_gram))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_bleu_multiple_references_picks_best_match():
    preds = ["the cat sat on the mat"]
    multi = [["the cat sat on the mat", "completely different words entirely now yes"]]
    single = [["completely different words entirely now yes"]]
    assert float(bleu_score(preds, multi)) > float(bleu_score(preds, single))


def test_sacrebleu_tokenization_differs_on_punctuation():
    preds = ["hello, world!"]
    target = [["hello , world !"]]
    plain = float(bleu_score(preds, target))
    sacre = float(sacre_bleu_score(preds, target, tokenize="13a"))
    assert sacre > plain  # 13a splits the punctuation, plain whitespace does not


def test_perfect_bleu_is_one():
    np.testing.assert_allclose(float(bleu_score(_TARGET, [[t] for t in _TARGET])), 1.0, atol=1e-6)


# ------------------------------------------------------------------ ROUGE / CHRF / TER / EED


def test_rouge_perfect_and_disjoint():
    perfect = rouge_score(_TARGET, _TARGET)
    for k in ("rouge1_fmeasure", "rouge2_fmeasure", "rougeL_fmeasure"):
        np.testing.assert_allclose(float(perfect[k]), 1.0, atol=1e-6, err_msg=k)
    disjoint = rouge_score(["aa bb cc"], ["xx yy zz"])
    np.testing.assert_allclose(float(disjoint["rouge1_fmeasure"]), 0.0, atol=1e-7)


def test_rouge_l_order_sensitivity():
    """rougeL uses the LCS: scrambling word order lowers it while rouge1 is unchanged."""
    straight = rouge_score(["a b c d e"], ["a b c d e"])
    scrambled = rouge_score(["e d c b a"], ["a b c d e"])
    np.testing.assert_allclose(float(scrambled["rouge1_fmeasure"]), float(straight["rouge1_fmeasure"]), atol=1e-6)
    assert float(scrambled["rougeL_fmeasure"]) < float(straight["rougeL_fmeasure"])


def test_chrf_bounds_and_ordering():
    perfect = float(chrf_score(_TARGET, [[t] for t in _TARGET]))
    np.testing.assert_allclose(perfect, 1.0, atol=1e-4)
    noisy = float(chrf_score(_PREDS, [[t] for t in _TARGET]))
    assert 0.0 < noisy < perfect


def test_ter_identical_and_shift():
    np.testing.assert_allclose(float(translation_edit_rate(_TARGET, [[t] for t in _TARGET])), 0.0, atol=1e-7)
    # one block shift costs 1 edit in tercom semantics, not the 4 of plain WER
    shifted = float(translation_edit_rate(["d a b c"], [["a b c d"]]))
    assert shifted <= 2 / 4 + 1e-6


def test_eed_reference_fixture_goldens():
    """Exact rwth-pinned values from the reference's own test fixtures
    (``tests/unittests/text/test_eed.py:32-33``, batch averages decomposed):
    these pin full per-pair parity with the published EED implementation."""
    a = "It is a guide to action which ensures that the military always obeys the commands of the party"
    r1a = "It is a guide to action that ensures that the military will forever heed Party commands"
    b = "he read the book because he was interested in world history"
    r1b = "he was interested in world history because he read the book"
    c = "the cat the   cat on the mat "
    r1c = "the  cat is     on the mat "
    pair_scores = {
        (a, r1a): 0.33268482,
        (b, r1b): 0.15227630,
        (c, r1c): 0.23076923,
    }
    for (hyp, ref), want in pair_scores.items():
        np.testing.assert_allclose(float(extended_edit_distance([hyp], [[ref]])), want, atol=1e-6)
    # ans_1 / ans_2 from the reference fixture are the two batch means
    np.testing.assert_allclose(
        float(extended_edit_distance([a, b], [[r1a], [r1b]])), 0.24248056, atol=1e-6
    )
    np.testing.assert_allclose(
        float(extended_edit_distance([b, c], [[r1b], [r1c]])), 0.19152276, atol=1e-6
    )


def test_eed_identical_small_positive():
    """EED of identical sentences is small but NOT zero — the rwth coverage term
    charges revisits even on the diagonal (faithful to the published algorithm)."""
    val = float(extended_edit_distance(["the quick brown fox"], [["the quick brown fox"]]))
    assert 0.0 < val < 0.05


# ------------------------------------------------------------------ SQuAD


def test_squad_exact_match_and_f1():
    preds = [{"prediction_text": "the cat", "id": "1"}, {"prediction_text": "a dog", "id": "2"}]
    target = [
        {"answers": {"answer_start": [0], "text": ["the cat"]}, "id": "1"},
        {"answers": {"answer_start": [0], "text": ["the hound"]}, "id": "2"},
    ]
    out = squad(preds, target)
    np.testing.assert_allclose(float(out["exact_match"]), 50.0, atol=1e-6)
    # pair 2: f1 over token overlap {a dog} vs {the hound} = 0
    np.testing.assert_allclose(float(out["f1"]), 50.0, atol=1e-4)


def test_squad_articles_normalized():
    preds = [{"prediction_text": "The Cat", "id": "1"}]
    target = [{"answers": {"answer_start": [0], "text": ["cat"]}, "id": "1"}]
    out = squad(preds, target)  # casing + leading article stripped by normalization
    np.testing.assert_allclose(float(out["exact_match"]), 100.0, atol=1e-6)
