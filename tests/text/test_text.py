"""Text suite: sacrebleu / nltk / rouge_score goldens (the reference's own golden libs,
``tests/unittests/text/``) plus hand-rolled counters, through the MetricTester protocol.
"""

from __future__ import annotations

import numpy as np
import pytest
import jax.numpy as jnp
from functools import lru_cache

import sacrebleu as sb
from nltk.translate.bleu_score import SmoothingFunction, corpus_bleu
from rouge_score.rouge_scorer import RougeScorer

from tests.testers import MetricTester
from torchmetrics_tpu.functional import (
    bleu_score,
    char_error_rate,
    chrf_score,
    extended_edit_distance,
    match_error_rate,
    perplexity,
    rouge_score,
    sacre_bleu_score,
    squad,
    translation_edit_rate,
    word_error_rate,
    word_information_lost,
    word_information_preserved,
)
from torchmetrics_tpu.text import (
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    ExtendedEditDistance,
    MatchErrorRate,
    Perplexity,
    ROUGEScore,
    SacreBLEUScore,
    SQuAD,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

# WMT-style fixture pairs (reference uses WMT16 samples)
PREDS_A = ["the cat sat on the mat", "there is a big tree near the house"]
TARGET_A = ["the cat sat on the mat", "there is a large tree near the house"]
PREDS_B = ["a quick brown fox jumps over the dog", "hello world this is a test"]
TARGET_B = ["the quick brown fox jumps over the lazy dog", "hello world it is a test"]

BATCHES_PREDS = [PREDS_A, PREDS_B]
BATCHES_TARGET = [TARGET_A, TARGET_B]
# multi-reference versions
BATCHES_TARGET_MULTI = [[[t, t.upper()] for t in TARGET_A], [[t, t.upper()] for t in TARGET_B]]


def _edit_golden(a, b):
    """Independent recursive-memo Levenshtein."""

    @lru_cache(maxsize=None)
    def d(i, j):
        if i == 0:
            return j
        if j == 0:
            return i
        return min(
            d(i - 1, j) + 1,
            d(i, j - 1) + 1,
            d(i - 1, j - 1) + (a[i - 1] != b[j - 1]),
        )

    return d(len(a), len(b))


def _wer_golden(preds, target):
    errs = sum(_edit_golden(tuple(p.split()), tuple(t.split())) for p, t in zip(preds, target))
    total = sum(len(t.split()) for t in target)
    return errs / total


def _cer_golden(preds, target):
    errs = sum(_edit_golden(tuple(p), tuple(t)) for p, t in zip(preds, target))
    total = sum(len(t) for t in target)
    return errs / total


def _mer_golden(preds, target):
    errs = sum(_edit_golden(tuple(p.split()), tuple(t.split())) for p, t in zip(preds, target))
    total = sum(max(len(t.split()), len(p.split())) for p, t in zip(preds, target))
    return errs / total


def _wil_wip_stats(preds, target):
    errs = sum(_edit_golden(tuple(p.split()), tuple(t.split())) for p, t in zip(preds, target))
    total = sum(max(len(t.split()), len(p.split())) for p, t in zip(preds, target))
    tt = sum(len(t.split()) for t in target)
    pt = sum(len(p.split()) for p in preds)
    h = errs - total  # the reference's (errors - total) statistic
    return h, tt, pt


def _wil_golden(preds, target):
    h, tt, pt = _wil_wip_stats(preds, target)
    return 1 - (h / tt) * (h / pt)


def _wip_golden(preds, target):
    h, tt, pt = _wil_wip_stats(preds, target)
    return (h / tt) * (h / pt)


class TestWerFamily(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize(
        "cls,fn,golden",
        [
            (WordErrorRate, word_error_rate, _wer_golden),
            (CharErrorRate, char_error_rate, _cer_golden),
            (MatchErrorRate, match_error_rate, _mer_golden),
            (WordInfoLost, word_information_lost, _wil_golden),
            (WordInfoPreserved, word_information_preserved, _wip_golden),
        ],
        ids=["wer", "cer", "mer", "wil", "wip"],
    )
    def test_class_and_functional(self, cls, fn, golden):
        self.run_class_metric_test(BATCHES_PREDS, BATCHES_TARGET, cls, golden)
        self.run_functional_metric_test(BATCHES_PREDS, BATCHES_TARGET, fn, golden, check_jit=False)


class TestBLEU(MetricTester):
    atol = 1e-5

    def test_vs_nltk(self):
        def golden(preds, target):
            refs = [[t.split()] for t in target]
            hyps = [p.split() for p in preds]
            return corpus_bleu(refs, hyps)

        self.run_class_metric_test(BATCHES_PREDS, BATCHES_TARGET, BLEUScore, golden)
        self.run_functional_metric_test(BATCHES_PREDS, BATCHES_TARGET, bleu_score, golden, check_jit=False)

    def test_smooth_vs_nltk(self):
        def golden(preds, target):
            refs = [[t.split()] for t in target]
            hyps = [p.split() for p in preds]
            return corpus_bleu(refs, hyps, smoothing_function=SmoothingFunction().method2)

        self.run_class_metric_test(
            BATCHES_PREDS, BATCHES_TARGET, BLEUScore, golden, metric_args={"smooth": True}
        )

    def test_multi_reference(self):
        all_preds = PREDS_A + PREDS_B
        all_targets = [[t] for t in TARGET_A] + [[t] for t in TARGET_B]
        got = float(bleu_score(all_preds, all_targets))
        refs = [[t[0].split()] for t in all_targets]
        want = corpus_bleu(refs, [p.split() for p in all_preds])
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestSacreBLEU(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("tokenize", ["13a", "char", "intl", "none"])
    @pytest.mark.parametrize("lowercase", [False, True])
    def test_vs_sacrebleu(self, tokenize, lowercase):
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        got = float(
            sacre_bleu_score(all_preds, [[t] for t in all_targets], tokenize=tokenize, lowercase=lowercase)
        )
        metric = sb.metrics.BLEU(tokenize=tokenize, lowercase=lowercase, effective_order=False)
        want = metric.corpus_score(all_preds, [all_targets]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_class_accumulation(self):
        m = SacreBLEUScore()
        for preds, target in zip(BATCHES_PREDS, BATCHES_TARGET):
            m.update(preds, [[t] for t in target])
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        want = sb.metrics.BLEU(effective_order=False).corpus_score(all_preds, [all_targets]).score / 100
        np.testing.assert_allclose(float(m.compute()), want, atol=1e-5)


class TestCHRF(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("n_word_order", [0, 2])
    def test_vs_sacrebleu(self, n_word_order):
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        got = float(chrf_score(all_preds, [[t] for t in all_targets], n_word_order=n_word_order))
        metric = sb.metrics.CHRF(word_order=n_word_order)
        want = metric.corpus_score(all_preds, [all_targets]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_class_accumulation_matches_corpus(self):
        m = CHRFScore()
        for preds, target in zip(BATCHES_PREDS, BATCHES_TARGET):
            m.update(preds, [[t] for t in target])
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        want = float(chrf_score(all_preds, [[t] for t in all_targets]))
        np.testing.assert_allclose(float(m.compute()), want, atol=1e-6)

    def test_sentence_level_scores(self):
        score, sentences = chrf_score(PREDS_A, [[t] for t in TARGET_A], return_sentence_level_score=True)
        assert sentences.shape == (2,)
        assert float(sentences[0]) == pytest.approx(1.0, abs=1e-6)  # identical pair


class TestTER(MetricTester):
    atol = 1e-5

    def test_vs_sacrebleu(self):
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        got = float(translation_edit_rate(all_preds, [[t] for t in all_targets]))
        metric = sb.metrics.TER()
        want = metric.corpus_score(all_preds, [all_targets]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-5)

    @pytest.mark.parametrize("kwargs", [{"normalize": True}, {"lowercase": False}, {"no_punctuation": True}])
    def test_vs_sacrebleu_options(self, kwargs):
        preds = ["A Quick, brown fox! jumped?", "the cat; sat. on the mat"]
        target = ["a quick brown fox jumped", "The cat sat on the Mat."]
        got = float(translation_edit_rate(preds, [[t] for t in target], **kwargs))
        sb_kwargs = {
            "normalized": kwargs.get("normalize", False),
            "no_punct": kwargs.get("no_punctuation", False),
            "case_sensitive": not kwargs.get("lowercase", True),
        }
        want = sb.metrics.TER(**sb_kwargs).corpus_score(preds, [target]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_class_accumulation(self):
        m = TranslationEditRate()
        for preds, target in zip(BATCHES_PREDS, BATCHES_TARGET):
            m.update(preds, [[t] for t in target])
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        want = sb.metrics.TER().corpus_score(all_preds, [all_targets]).score / 100
        np.testing.assert_allclose(float(m.compute()), want, atol=1e-5)


class TestROUGE(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("key", ["rouge1", "rouge2", "rougeL"])
    def test_vs_rouge_score(self, key):
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        got = rouge_score(all_preds, all_targets, rouge_keys=key)
        scorer = RougeScorer([key], use_stemmer=False)
        scores = [scorer.score(t, p)[key] for p, t in zip(all_preds, all_targets)]
        np.testing.assert_allclose(
            float(got[f"{key}_fmeasure"]), np.mean([s.fmeasure for s in scores]), atol=1e-5
        )
        np.testing.assert_allclose(
            float(got[f"{key}_precision"]), np.mean([s.precision for s in scores]), atol=1e-5
        )
        np.testing.assert_allclose(
            float(got[f"{key}_recall"]), np.mean([s.recall for s in scores]), atol=1e-5
        )

    def test_rouge_lsum(self):
        # newline-pre-split summaries: rouge_score's default Lsum path splits on "\n"
        preds = ["the cat sat\nthe dog barked loudly"]
        target = ["the cat sat on the mat\na dog barked"]
        got = rouge_score(preds, target, rouge_keys="rougeLsum")
        scorer = RougeScorer(["rougeLsum"], use_stemmer=False)
        want = scorer.score(target[0], preds[0])["rougeLsum"]
        np.testing.assert_allclose(float(got["rougeLsum_fmeasure"]), want.fmeasure, atol=1e-5)

    def test_class_accumulation(self):
        m = ROUGEScore(rouge_keys=("rouge1", "rougeL"))
        for preds, target in zip(BATCHES_PREDS, BATCHES_TARGET):
            m.update(preds, target)
        out = m.compute()
        all_preds = PREDS_A + PREDS_B
        all_targets = TARGET_A + TARGET_B
        want = rouge_score(all_preds, all_targets, rouge_keys=("rouge1", "rougeL"))
        for k in out:
            np.testing.assert_allclose(float(out[k]), float(want[k]), atol=1e-6)


class TestPerplexity(MetricTester):
    atol = 1e-4

    def test_vs_torch(self):
        import torch
        import torch.nn.functional as F

        rng = np.random.default_rng(3)
        logits = rng.normal(size=(2, 8, 16)).astype(np.float32)
        target = rng.integers(0, 16, size=(2, 8))

        got = float(perplexity(jnp.asarray(logits), jnp.asarray(target)))
        t_logits = torch.tensor(logits).reshape(-1, 16)
        t_target = torch.tensor(target).reshape(-1)
        want = torch.exp(F.cross_entropy(t_logits, t_target)).item()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_ignore_index(self):
        rng = np.random.default_rng(4)
        logits = rng.normal(size=(2, 8, 16)).astype(np.float32)
        target = rng.integers(0, 16, size=(2, 8))
        target[0, :4] = -100

        import torch
        import torch.nn.functional as F

        got = float(perplexity(jnp.asarray(logits), jnp.asarray(target), ignore_index=-100))
        want = torch.exp(
            F.cross_entropy(torch.tensor(logits).reshape(-1, 16), torch.tensor(target).reshape(-1), ignore_index=-100)
        ).item()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_class_accumulation(self):
        rng = np.random.default_rng(5)
        m = Perplexity()
        all_logits, all_targets = [], []
        for _ in range(3):
            logits = rng.normal(size=(2, 6, 10)).astype(np.float32)
            target = rng.integers(0, 10, size=(2, 6))
            all_logits.append(logits)
            all_targets.append(target)
            m.update(jnp.asarray(logits), jnp.asarray(target))
        want = float(
            perplexity(jnp.asarray(np.concatenate(all_logits)), jnp.asarray(np.concatenate(all_targets)))
        )
        np.testing.assert_allclose(float(m.compute()), want, rtol=1e-6)


class TestSquad(MetricTester):
    def test_known_values(self):
        preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        out = squad(preds, target)
        assert float(out["exact_match"]) == 100.0
        assert float(out["f1"]) == 100.0

    def test_partial_match(self):
        preds = [{"prediction_text": "large green tree", "id": "a"}]
        target = [{"answers": {"answer_start": [0], "text": ["green tree"]}, "id": "a"}]
        out = squad(preds, target)
        assert float(out["exact_match"]) == 0.0
        np.testing.assert_allclose(float(out["f1"]), 2 * (2 / 3) * (2 / 2) / ((2 / 3) + 1.0) * 100, atol=1e-4)

    def test_class(self):
        m = SQuAD()
        m.update(
            [{"prediction_text": "1976", "id": "x"}],
            [{"answers": {"text": ["1976"]}, "id": "x"}],
        )
        m.update(
            [{"prediction_text": "wrong", "id": "y"}],
            [{"answers": {"text": ["right"]}, "id": "y"}],
        )
        out = m.compute()
        assert float(out["exact_match"]) == 50.0


class TestEED(MetricTester):
    def test_identical_pair_coverage_floor(self):
        """Identical strings score rho/(L+rho) — the coverage cost of the unvisited cell."""
        sent = PREDS_A[0]
        got = float(extended_edit_distance([sent], [[sent]]))
        length = len(f" {sent} ")  # en preprocessing pads with spaces
        want = 0.3 / (length + 0.3)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_monotone(self):
        close = float(extended_edit_distance(["the cat sat on the mat"], [["the cat sat on a mat"]]))
        far = float(extended_edit_distance(["the cat sat on the mat"], [["completely different words here"]]))
        assert 0 < close < far <= 1

    def test_reference_doctest_value(self):
        # reference eed.py doctest: hyps/refs below -> 0.3078
        preds = ["this is the prediction", "here is an other sample"]
        target = ["this is the reference", "here is another one"]
        got = float(extended_edit_distance(preds, target))
        np.testing.assert_allclose(got, 0.3078, atol=1e-3)

    def test_class_accumulation(self):
        m = ExtendedEditDistance()
        for preds, target in zip(BATCHES_PREDS, BATCHES_TARGET):
            m.update(preds, [[t] for t in target])
        all_preds = PREDS_A + PREDS_B
        all_targets = [[t] for t in TARGET_A + TARGET_B]
        want = float(extended_edit_distance(all_preds, all_targets))
        np.testing.assert_allclose(float(m.compute()), want, atol=1e-6)
