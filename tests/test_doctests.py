"""Every docstring example is itself a test (reference ``pyproject.toml:28-31`` runs
``--doctest-modules`` over the whole package; here doctests are collected explicitly so
the CPU-mesh conftest env applies)."""

import doctest
import importlib
import pkgutil

import pytest

import torchmetrics_tpu


def _modules_with_doctests():
    found = []
    for info in pkgutil.walk_packages(torchmetrics_tpu.__path__, prefix="torchmetrics_tpu."):
        if "native" in info.name:  # requires the compiled C++ library
            continue
        try:
            mod = importlib.import_module(info.name)
        except Exception:
            continue
        if doctest.DocTestFinder().find(mod) and any(
            t.examples for t in doctest.DocTestFinder().find(mod)
        ):
            found.append(info.name)
    return sorted(found)


_MODULES = _modules_with_doctests()


def test_doctest_modules_discovered():
    # guard against the discovery silently collapsing (the r5 example sweep
    # brought the package to reference-style density: 219 reference modules
    # carry >>> blocks, this package holds >=150)
    assert len(_MODULES) >= 150, len(_MODULES)


def test_every_wrapper_has_doctest():
    # the wrapper layer once shipped without a single executable example
    wrapper_mods = [m for m in _MODULES if m.startswith("torchmetrics_tpu.wrappers.")]
    assert len(wrapper_mods) >= 7, wrapper_mods


@pytest.mark.parametrize("module_name", _MODULES)
def test_doctest(module_name):
    mod = importlib.import_module(module_name)
    results = doctest.testmod(mod, verbose=False, optionflags=doctest.NORMALIZE_WHITESPACE)
    assert results.failed == 0, f"{results.failed} doctest failures in {module_name}"
