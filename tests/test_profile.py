"""Profiling-layer tests (diag/profile.py + diag/hist.py + diag/timeline.py):
histogram quantile error bounds, sampled completion probes under the strict
transfer guard, packed-sync straggler detection in an emulated two-rank world
(one rank genuinely sleeping pre-sync), merged-timeline determinism, and the
profile-off zero-probe invariant."""

import json
import time
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu.diag import (
    diag_context,
    export_prometheus,
    profile_context,
    profile_snapshot,
    telemetry_snapshot,
    transfer_guard,
)
from torchmetrics_tpu.diag import hist as hist_mod
from torchmetrics_tpu.diag import profile as profile_mod
from torchmetrics_tpu.diag import timeline as timeline_mod
from torchmetrics_tpu.diag.hist import GROWTH, Histogram
from torchmetrics_tpu.diag.timeline import merge_timelines, resolve_arrivals, stamp_arrival
from torchmetrics_tpu.engine import engine_context, engine_report, reset_engine_stats
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

DISTRIBUTED = staticmethod(lambda: True)


@pytest.fixture(autouse=True)
def _clean_stats():
    reset_engine_stats()
    yield
    reset_engine_stats()


class FloatSum(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + x.sum()

    def compute(self):
        return self.total


def _world2(monkeypatch, gather):
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather", gather)


# ------------------------------------------------------------------ histograms


@pytest.mark.parametrize("q", [0.5, 0.9, 0.99])
def test_histogram_quantile_error_bound_vs_exact(q):
    """The estimate is the upper bound of the exact sample's bucket: for every
    in-range distribution, exact <= estimate <= exact * GROWTH."""
    rng = np.random.RandomState(17)
    for samples in (
        rng.lognormal(mean=4.0, sigma=1.5, size=5000),  # latency-shaped tail
        rng.uniform(1.0, 1e6, size=3000),
        np.full(100, 42.0),  # degenerate: all equal
    ):
        hist = Histogram()
        for v in samples:
            hist.record(float(v))
        exact = float(np.sort(samples)[max(1, int(np.ceil(q * len(samples)))) - 1])
        est = hist.quantile(q)
        assert est >= exact * (1 - 1e-9), f"q={q}: {est} < exact {exact}"
        assert est <= exact * GROWTH * (1 + 1e-9), f"q={q}: {est} > {GROWTH}x exact {exact}"


def test_histogram_fixed_memory_and_overflow():
    hist = Histogram()
    n_slots = len(hist.counts)
    for v in [0.0, 1e-9, 3.5, 1e12, 2.5e9]:  # under- and overflow included
        hist.record(v)
    hist.record(float("nan"))  # must not poison sum/min/max
    assert len(hist.counts) == n_slots  # bounded: no per-event storage
    assert hist.total == 5
    assert hist.max == 1e12
    # overflow ranks report the recorded max, not a fake top boundary
    assert hist.quantile(1.0) == 1e12
    # the cumulative bucket list ends with the +Inf bucket == total count
    assert hist.nonempty_buckets()[-1] == (None, 5)


def test_histogram_registry_snapshot_sorted_and_reset():
    hist_mod.observe("B", "update", "dispatch_us", 5.0)
    hist_mod.observe("A", "update", "dispatch_us", 2.0)
    rows = hist_mod.histograms_snapshot()
    assert [r["owner"] for r in rows] == ["A", "B"]
    assert all(r["count"] == 1 and r["p50"] is not None for r in rows)
    hist_mod.reset_histograms()
    assert hist_mod.histograms_snapshot() == []


# ------------------------------------------------------------------ probes


def test_profile_off_records_zero_probes():
    with engine_context(True), diag_context() as rec:
        m = FloatSum(compiled_update=True)
        for _ in range(6):
            m.update(jnp.ones((4,)))
    assert profile_snapshot()["probes"] == 0
    assert engine_report()["profile_probes"] == 0
    assert rec.counts.get("update.probe", 0) == 0
    # no device_us series was fed either
    assert not any(r["series"] == "device_us" for r in hist_mod.histograms_snapshot())


def test_sampled_probes_under_strict_guard():
    """Every Nth warm dispatch blocks at a sanctioned boundary: device_us is
    measured, and the strict transfer guard stays silent throughout."""
    with engine_context(True), profile_context(every_n=2), diag_context() as rec, transfer_guard("strict"):
        m = FloatSum(compiled_update=True)
        for _ in range(9):  # 1 cold + 8 warm -> 4 probes at every_n=2
            m.update(jnp.ones((4,)))
    assert rec.count("transfer.host", "transfer.blocked") == 0
    probes = [e for e in rec.snapshot() if e.kind == "update.probe"]
    assert len(probes) == 4
    assert all(e.data["device_us"] > 0 for e in probes)
    assert engine_report()["profile_probes"] == 4
    snap = profile_snapshot()
    assert snap["probes"] == 4 and snap["per_site"]["FloatSum:update"]["warm_dispatches"] == 8
    rows = {(r["kind"], r["series"]): r for r in hist_mod.histograms_snapshot()}
    assert rows[("update", "device_us")]["count"] == 4
    assert rows[("update", "dispatch_us")]["count"] == 9


def test_dispatch_events_carry_dispatch_us_only():
    with engine_context(True), diag_context() as rec:
        m = FloatSum(compiled_update=True)
        m.update(jnp.ones((4,)))
    (ev,) = [e for e in rec.snapshot() if e.kind == "update.dispatch"]
    assert ev.data["dispatch_us"] > 0
    assert "dur_us" not in ev.data  # deprecated alias retired after its one-release window


def test_eager_update_timed_into_histograms():
    with diag_context() as rec:
        m = FloatSum(compiled_update=False)
        m.update(jnp.ones((4,)))
    (ev,) = [e for e in rec.snapshot() if e.kind == "update.eager"]
    assert ev.data["dispatch_us"] > 0 and "dur_us" not in ev.data
    assert any(
        r["kind"] == "eager" and r["series"] == "dispatch_us" for r in hist_mod.histograms_snapshot()
    )


def test_profile_context_validates_and_env_parsing(monkeypatch):
    with pytest.raises(ValueError):
        profile_context(every_n=0).__enter__()
    monkeypatch.setenv(profile_mod.PROFILE_ENV_VAR, "8")
    assert profile_mod.active_profile() == 8
    monkeypatch.setenv(profile_mod.PROFILE_ENV_VAR, "1")
    assert profile_mod.active_profile() == profile_mod.DEFAULT_EVERY_N
    monkeypatch.setenv(profile_mod.PROFILE_ENV_VAR, "0")
    assert profile_mod.active_profile() is None


# ------------------------------------------------------------------ straggler


def test_planted_straggler_world2_attributes_correct_rank(monkeypatch):
    """World-2 in-process; 'rank 1' genuinely sleeps before stamping its
    barrier arrival. After a calibration sync, the skew is measured, the
    straggler flag counts, and the event names rank 1 — all under STRICT."""
    plant = {"on": False}

    def gather(x, tiled=False):
        # the metadata probe is the only HOST ndarray through the gather —
        # state buffers arrive as jax arrays and must never be perturbed
        is_meta = isinstance(x, np.ndarray) and x.ndim == 1 and x.dtype == np.int32
        arr = np.asarray(x)
        rows = [arr, arr]
        if plant["on"] and is_meta:
            time.sleep(0.03)  # rank 1 straggles into the packed sync
            rows[1] = stamp_arrival(arr)
        return np.stack(rows)

    _world2(monkeypatch, gather)
    with engine_context(True), profile_context(every_n=4), diag_context() as rec, transfer_guard("strict"):
        m = FloatSum(compiled_update=True)
        m.distributed_available_fn = DISTRIBUTED
        m.update(jnp.ones((4,)))
        m.compute()  # calibration sync: anchors the barrier-exit stamps
        eng = m._epoch
        assert eng.stats.sync_straggler_flags == 0
        m.reset()
        m.update(jnp.ones((4,)))
        plant["on"] = True
        m.compute()
    assert eng.stats.sync_straggler_flags == 1
    (ev,) = [e for e in rec.snapshot() if e.kind == "sync.straggler"]
    assert ev.data["rank"] == 1
    assert 20_000 < ev.data["skew_us"] < 2_000_000  # ~30 ms sleep, generous slack
    assert rec.count("transfer.host", "transfer.blocked") == 0


def test_straggler_below_threshold_not_flagged(monkeypatch):
    _world2(monkeypatch, lambda x, tiled=False: np.stack([np.asarray(x)] * 2))
    with engine_context(True), profile_context(every_n=4), diag_context() as rec:
        m = FloatSum(compiled_update=True)
        m.distributed_available_fn = DISTRIBUTED
        for _ in range(2):  # identical-rank stamps: zero skew, even calibrated
            m.update(jnp.ones((4,)))
            m.compute()
            m.reset()
    assert engine_report()["sync_straggler_flags"] == 0
    assert rec.counts.get("sync.straggler", 0) == 0


def test_timeline_layout_version_mismatch_fails_loud(monkeypatch):
    """A rank gathering a foreign layout version (asymmetric profiling
    enablement) must error on the metadata, not mis-parse it."""

    def gather(x, tiled=False):
        is_meta = isinstance(x, np.ndarray) and x.ndim == 1 and x.dtype == np.int32
        arr = np.asarray(x)
        if not is_meta:
            return np.stack([arr, arr])
        bad = np.array(arr, copy=True)
        bad[-3] = 99  # the version slot of "rank 1"
        return np.stack([arr, bad])

    _world2(monkeypatch, gather)
    with engine_context(True), profile_context(every_n=4):
        m = FloatSum(compiled_update=True)
        m.distributed_available_fn = DISTRIBUTED
        m.update(jnp.ones((4,)))
        with pytest.raises(TorchMetricsUserError, match="timeline layout"):
            m.compute()


def test_resolve_arrivals_offset_correction():
    # rank 1's clock runs 500 us ahead: same true arrival, skewed raw stamps
    res = resolve_arrivals(prev_post=[1000, 1500], arrivals=[2000, 2500], local_rank=0)
    assert res["calibrated"] and res["offsets_us"] == [0, 500]
    assert res["corrected_us"] == [2000, 2000] and res["skew_us"] == 0
    # uncalibrated (a rank has no prior sync): raw arrivals, zero offsets
    res = resolve_arrivals(prev_post=[0, 1500], arrivals=[2000, 2600], local_rank=0)
    assert not res["calibrated"] and res["offsets_us"] == [0, 0]
    assert res["skew_us"] == 600 and res["last_rank"] == 1


# ------------------------------------------------------------------ timeline merge


def _two_rank_streams():
    streams = []
    for rank, offset in ((0, 0.0), (1, 250.0)):
        with diag_context() as rec:
            m = FloatSum(compiled_update=True)
            with engine_context(True):
                for _ in range(3):
                    m.update(jnp.ones((2,)))
        streams.append({"rank": rank, "events": rec.snapshot(), "clock_offset_us": offset})
    return streams


def test_merge_timelines_deterministic_and_monotone(tmp_path):
    streams = _two_rank_streams()
    trace_a = merge_timelines(streams, path=str(tmp_path / "merged.json"))
    trace_b = merge_timelines(streams)
    # byte-stable: identical inputs serialize identically
    assert json.dumps(trace_a, sort_keys=True) == json.dumps(trace_b, sort_keys=True)
    with open(tmp_path / "merged.json") as fh:
        assert json.load(fh) == trace_a
    events = trace_a["traceEvents"]
    # per-rank process tracks with metadata names
    names = {e["pid"]: e["args"]["name"] for e in events if e.get("name") == "process_name"}
    assert names == {0: "rank 0", 1: "rank 1"}
    # per-rank clocks stay monotone after offset correction (slices compare by
    # END time = ts + dur; the recorder stamps events at completion)
    for rank in (0, 1):
        ends = [
            e["ts"] + e.get("dur", 0.0)
            for e in events
            if e.get("pid") == rank and e.get("ph") in ("X", "i")
        ]
        assert ends == sorted(ends)


def test_merge_timelines_accepts_export_json_shape():
    streams = _two_rank_streams()
    as_dicts = [
        {
            "rank": s["rank"],
            "clock_offset_us": s["clock_offset_us"],
            "events": [
                {"seq": e.seq, "ts_us": e.ts * 1e6, "kind": e.kind, "owner": e.owner, **e.data}
                for e in s["events"]
            ],
        }
        for s in streams
    ]
    a = merge_timelines(streams)
    b = merge_timelines(as_dicts)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def _fleet_streams():
    """2 pods × 2 ranks with per-(pod, rank) clock offsets (PR-19 fleet shape)."""
    streams = []
    for pod, rank, offset in (
        ("us-west", 0, 0.0), ("us-west", 1, 250.0),
        ("eu-hub", 0, 125.0), ("eu-hub", 1, 375.0),
    ):
        with diag_context() as rec:
            m = FloatSum(compiled_update=True)
            with engine_context(True):
                for _ in range(2):
                    m.update(jnp.ones((2,)))
        streams.append({
            "pod": pod, "rank": rank,
            "events": rec.snapshot(), "clock_offset_us": offset,
        })
    return streams


def test_merge_timelines_fleet_pod_tracks(tmp_path):
    streams = _fleet_streams()
    trace = merge_timelines(streams, path=str(tmp_path / "fleet.json"))
    events = trace["traceEvents"]
    names = {e["pid"]: e["args"]["name"] for e in events if e.get("name") == "process_name"}
    # dense pids in canonical (pod, rank) order — two pods' rank 0 never collide
    assert names == {
        0: "pod eu-hub · rank 0", 1: "pod eu-hub · rank 1",
        2: "pod us-west · rank 0", 3: "pod us-west · rank 1",
    }
    # per-stream clocks stay monotone after per-pod offset correction
    for pid in names:
        ends = [
            e["ts"] + e.get("dur", 0.0)
            for e in events
            if e.get("pid") == pid and e.get("ph") in ("X", "i")
        ]
        assert ends and ends == sorted(ends)


def test_merge_timelines_fleet_permutation_stable():
    """The canonical (pod, rank) sort — not arrival order — fixes every pid:
    any permutation of the fleet's streams serializes byte-identically."""
    streams = _fleet_streams()
    baseline = json.dumps(merge_timelines(streams), sort_keys=True)
    for order in ((3, 1, 0, 2), (2, 3, 0, 1), (1, 0, 3, 2)):
        permuted = [streams[i] for i in order]
        assert json.dumps(merge_timelines(permuted), sort_keys=True) == baseline


# ------------------------------------------------------------------ exposition


def test_prometheus_histogram_exposition_conformance():
    from tests.test_telemetry import parse_exposition

    with engine_context(True), profile_context(every_n=2), diag_context():
        m = FloatSum(compiled_update=True)
        for _ in range(7):
            m.update(jnp.ones((4,)))
    text = export_prometheus()
    samples, types = parse_exposition(text)
    fam = "tm_tpu_dispatch_latency_seconds"
    assert types[fam] == "histogram"
    assert types["tm_tpu_device_latency_seconds"] == "histogram"
    buckets = [
        (labels, v) for (name, labels), v in samples.items() if name == f"{fam}_bucket"
        and any(l.startswith('kind="update"') for l in labels)
    ]
    assert buckets, "no _bucket samples for the update dispatch histogram"
    # cumulative counts are monotone in le, and +Inf equals _count
    def le_of(labels):
        raw = next(l for l in labels if l.startswith('le="')).split('"')[1]
        return float("inf") if raw == "+Inf" else float(raw)

    ordered = sorted(buckets, key=lambda kv: le_of(kv[0]))
    values = [v for _, v in ordered]
    assert values == sorted(values)
    count_key = next(
        (name, labels) for (name, labels) in samples
        if name == f"{fam}_count" and any(l.startswith('kind="update"') for l in labels)
    )
    assert values[-1] == samples[count_key] == 7
    sum_key = (f"{fam}_sum", count_key[1])
    assert samples[sum_key] > 0
    # latency is exported in SECONDS: 7 dispatches on CPU take well under 7s
    assert samples[sum_key] < 7.0


def test_snapshot_includes_histograms_and_profile_sections():
    with engine_context(True), profile_context(every_n=2), diag_context():
        m = FloatSum(compiled_update=True)
        for _ in range(5):
            m.update(jnp.ones((4,)))
        snap = telemetry_snapshot()
    assert snap["profile"]["active"] and snap["profile"]["every_n"] == 2
    assert snap["profile"]["probes"] >= 1
    rows = {(r["kind"], r["series"]) for r in snap["histograms"]}
    assert ("update", "dispatch_us") in rows and ("update", "device_us") in rows
