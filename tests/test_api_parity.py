"""API-surface parity audit vs the reference package.

Walks every ``__all__`` the reference declares (root, ``functional``, and each domain
subpackage) and asserts the same name is importable from the corresponding
``torchmetrics_tpu`` module. Skips wherever the read-only reference checkout is not
mounted. Conditional reference exports (names gated on optional deps at reference
import time) are resolved from the reference's source text, not its runtime import,
so the audit covers the full declared surface.
"""

from __future__ import annotations

import ast
import importlib
from pathlib import Path

import pytest

_REF = Path("/root/reference/src/torchmetrics")

pytestmark = pytest.mark.skipif(not _REF.exists(), reason="reference checkout not mounted")

_MODULES = [
    "",
    "functional",
    "classification",
    "regression",
    "image",
    "text",
    "audio",
    "detection",
    "retrieval",
    "nominal",
    "multimodal",
    "wrappers",
    "functional.classification",
    "functional.regression",
    "functional.image",
    "functional.text",
    "functional.audio",
    "functional.detection",
    "functional.retrieval",
    "functional.nominal",
    "functional.pairwise",
    "functional.multimodal",
]


def _reference_all(module: str) -> list:
    path = _REF / module.replace(".", "/") / "__init__.py" if module else _REF / "__init__.py"
    tree = ast.parse(path.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(getattr(t, "id", None) == "__all__" for t in node.targets):
            return [ast.literal_eval(e) for e in node.value.elts]
    return []


@pytest.mark.parametrize("module", _MODULES, ids=[m or "root" for m in _MODULES])
def test_every_reference_export_exists(module):
    names = _reference_all(module)
    # an empty table means the parser no longer finds the reference's __all__
    # (layout/AST-shape change) — fail loudly instead of passing vacuously.
    # functional.multimodal/multimodal legitimately declare no names.
    if module not in ("multimodal", "functional.multimodal"):
        assert names, f"{module or 'root'}: reference __all__ not found — update _reference_all"
    ours = importlib.import_module(f"torchmetrics_tpu.{module}" if module else "torchmetrics_tpu")
    missing = [n for n in names if not hasattr(ours, n)]
    assert not missing, f"{module or 'root'}: missing {len(missing)}/{len(names)}: {missing}"


def test_parity_audit_covers_real_surface():
    # the audit is vacuous if the reference layout moved — require the big tables
    assert len(_reference_all("")) >= 90
    assert len(_reference_all("functional")) >= 90
    assert len(_reference_all("classification")) >= 90
