"""Async pipelined dispatch tests (engine/async_dispatch.py + engine/scan.py):
double-buffered background drains, the join contract, backpressure, caller-side
failure replay, prefetch staging, overlap attribution, the pause-free sidecar
scrape, and the concurrent-observer stress proof."""

import http.client
import signal
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from torchmetrics_tpu import MetricCollection, SumMetric
from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassPrecision
from torchmetrics_tpu.diag import diag_context, transfer_guard
from torchmetrics_tpu.diag.hist import histograms_snapshot
from torchmetrics_tpu.engine import (
    async_context,
    compensated_context,
    engine_context,
    quarantine_context,
    scan_context,
    set_async_dispatch,
)
from torchmetrics_tpu.engine.async_dispatch import (
    DEFAULT_INFLIGHT,
    MAX_INFLIGHT,
    async_inflight,
    coerce_inflight,
    note_epoch_sync,
    resolve_async,
)
from torchmetrics_tpu.engine import scan as scan_mod
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

NUM_CLASSES = 5


def _batches(sizes, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (jnp.asarray(rng.rand(n, NUM_CLASSES).astype(np.float32)),
         jnp.asarray(rng.randint(0, NUM_CLASSES, n).astype(np.int32)))
        for n in sizes
    ]


def _acc(**kw):
    return MulticlassAccuracy(NUM_CLASSES, average="macro", validate_args=False, **kw)


def _states(m):
    return {s: np.asarray(getattr(m, s)) for s in m._defaults}


# ---------------------------------------------------------------- knobs


def test_env_var_fail_loud(monkeypatch):
    """Invalid TORCHMETRICS_TPU_ASYNC values raise instead of silently disabling."""
    for bad in ("banana", "-1", str(MAX_INFLIGHT + 1), "1.5", "true"):
        monkeypatch.setenv("TORCHMETRICS_TPU_ASYNC", bad)
        with pytest.raises(TorchMetricsUserError):
            async_inflight()
    for off in ("", "0", "off"):
        monkeypatch.setenv("TORCHMETRICS_TPU_ASYNC", off)
        assert async_inflight() is None
    monkeypatch.setenv("TORCHMETRICS_TPU_ASYNC", "1")
    assert async_inflight() == DEFAULT_INFLIGHT
    monkeypatch.setenv("TORCHMETRICS_TPU_ASYNC", "on")
    assert async_inflight() == DEFAULT_INFLIGHT
    monkeypatch.setenv("TORCHMETRICS_TPU_ASYNC", "4")
    assert async_inflight() == 4


def test_kwarg_and_override_resolution(monkeypatch):
    monkeypatch.delenv("TORCHMETRICS_TPU_ASYNC", raising=False)
    assert async_inflight() is None
    assert coerce_inflight(True) == DEFAULT_INFLIGHT
    assert coerce_inflight(False) == 0
    assert coerce_inflight(3) == 3
    with pytest.raises(TorchMetricsUserError):
        coerce_inflight(MAX_INFLIGHT + 1)
    with pytest.raises(TorchMetricsUserError):
        coerce_inflight("2")
    with async_context():
        assert async_inflight() == DEFAULT_INFLIGHT
        # per-metric kwarg outranks the context: 0/False forces off
        m_off = _acc(async_dispatch=False)
        assert resolve_async(m_off.async_dispatch) is None
        m_on = _acc(async_dispatch=4)
        assert resolve_async(m_on.async_dispatch) == 4
    assert async_inflight() is None
    set_async_dispatch(3)
    try:
        assert async_inflight() == 3
    finally:
        set_async_dispatch(None)
    with pytest.raises(TorchMetricsUserError):
        _acc(async_dispatch="yes")
    with pytest.raises(TorchMetricsUserError):
        MetricCollection(
            {"a": _acc(), "b": MulticlassPrecision(NUM_CLASSES, validate_args=False)},
            async_dispatch=99,
        )


def test_engine_off_metric_never_reads_async_env(monkeypatch):
    """The env knob resolves only where a scan queue is active — a typo'd
    TORCHMETRICS_TPU_ASYNC cannot raise on configurations that never read it."""
    monkeypatch.setenv("TORCHMETRICS_TPU_ASYNC", "banana")
    p, t = _batches([4])[0]
    with engine_context(False):
        m = _acc()
        m.update(p, t)  # engine off: no scan queue, no async consult
        m.compute()
    with engine_context(True):
        m = _acc(scan_steps=0)  # scan forced off per metric: still no consult
        m.update(p, t)
        m.compute()
    with engine_context(True), scan_context(4):
        m = _acc()
        with pytest.raises(TorchMetricsUserError):
            m.update(p, t)  # scan active -> the knob IS read -> fail loud


# ---------------------------------------------------------------- core behavior


def test_async_parity_with_sync_scan_and_step_at_a_time():
    """Byte parity incl. a mid-queue quarantined batch + compensated sums."""
    stream = _batches([8] * 24, seed=3)
    nan_preds = jnp.asarray(np.full((8, NUM_CLASSES), np.nan, np.float32))
    poisoned = {5, 13}

    def run(scan_k, use_async):
        with engine_context(True, donate=True), quarantine_context(True), compensated_context(True):
            from contextlib import nullcontext

            with (scan_context(scan_k) if scan_k else nullcontext()), (
                async_context() if use_async else nullcontext()
            ):
                m = _acc()
                for i, (p, t) in enumerate(stream):
                    m.update(nan_preds if i in poisoned else p, t)
                value = np.asarray(m.compute())
                states = _states(m)
        return value, states

    ref_value, ref_states = run(0, False)
    sync_value, sync_states = run(8, False)
    async_value, async_states = run(8, True)
    assert np.array_equal(ref_value, async_value)
    assert np.array_equal(sync_value, async_value)
    for s in ref_states:
        assert np.array_equal(ref_states[s], async_states[s])
        assert np.array_equal(sync_states[s], async_states[s])


def test_background_drains_and_join_on_observation():
    stream = _batches([8] * 20, seed=1)
    with engine_context(True, donate=True), scan_context(4), async_context():
        m = _acc()
        for p, t in stream:
            m.update(p, t)
        st = m._engine.stats
        # 5 buffers total; the first drain per (signature, K-bucket) compiles
        # ON THE CALLER (incl. the x64 dtype-promotion re-key), the rest ride
        # the worker as submits
        assert st.async_submits >= 2
        value = m.compute()  # the JOIN: folds the tail + waits the FIFO dry
        assert st.scan_steps_folded == 20
        assert st.async_dispatches >= 2  # warm drains genuinely rode the worker
        assert st.scan_dispatches == 5  # 20 steps / K=4, caller-compiles included
        assert st.async_replayed_steps == 0
        assert m._update_count == 20
        np.asarray(value)


def test_backpressure_bounds_inflight_depth():
    from torchmetrics_tpu.diag.hist import reset_histograms

    reset_histograms()  # the depth histogram is process-wide; isolate from other tests
    stream = _batches([8] * 64, seed=2)
    with engine_context(True, donate=True), scan_context(4), async_context(1), diag_context():
        m = _acc()
        for p, t in stream:
            m.update(p, t)
        m.compute()
        st = m._engine.stats
    depth_rows = [
        h for h in histograms_snapshot()
        if h["kind"] == "async" and h["series"] == "depth" and h["owner"] == "MulticlassAccuracy"
    ]
    assert depth_rows and depth_rows[0]["max"] <= 1.0  # the bound held
    assert st.async_backpressure_waits > 0  # ...and was actually exercised


def test_worker_failure_replays_on_caller(monkeypatch):
    """A drain failing on the worker hands its payloads back: the next join
    replays step-at-a-time on the OBSERVER's thread — nothing is lost."""
    stream = _batches([8] * 12, seed=4)

    def boom(*args, **kwargs):
        raise RuntimeError("planted scan-compile failure")

    with engine_context(True, donate=True):
        ref = _acc()
        for p, t in stream:
            ref.update(p, t)
        ref_value = np.asarray(ref.compute())

        monkeypatch.setattr(scan_mod, "compile_scan", boom)
        with scan_context(4), async_context():
            m = _acc()
            for p, t in stream:
                m.update(p, t)
            value = np.asarray(m.compute())
            st = m._engine.stats
    assert np.array_equal(ref_value, value)
    assert m._update_count == 12
    assert st.async_replayed_steps > 0
    assert st.async_dispatches == 0  # no background drain ever succeeded
    assert any(r.startswith("scan-") for r in st.fallback_reasons)


def test_reset_discards_in_flight_settled():
    stream = _batches([8] * 7, seed=5)
    with engine_context(True, donate=True), scan_context(4), async_context():
        m = _acc()
        for p, t in stream:
            m.update(p, t)
        m.reset()  # joins in-flight work, DISCARDS the tail without dispatch
        assert m._update_count == 0
        for s, default in m._defaults.items():
            assert np.array_equal(np.asarray(getattr(m, s)), np.asarray(default))
        p, t = stream[0]
        m.update(p, t)
        value = np.asarray(m.compute())
        fresh = _acc(compiled_update=True)
        fresh.update(p, t)
    assert np.array_equal(value, np.asarray(fresh.compute()))


def test_fused_collection_async_parity():
    stream = _batches([8] * 16, seed=6)

    def run(use_async):
        from contextlib import nullcontext

        with engine_context(True, donate=True), scan_context(4), (
            async_context() if use_async else nullcontext()
        ):
            mc = MetricCollection(
                {
                    "acc": _acc(),
                    "prec": MulticlassPrecision(NUM_CLASSES, average="macro", validate_args=False),
                }
            )
            for p, t in stream:
                mc.update(p, t)
            values = {k: np.asarray(v) for k, v in mc.compute().items()}
        return values

    sync_values = run(False)
    async_values = run(True)
    assert set(sync_values) == set(async_values)
    for k in sync_values:
        assert np.array_equal(sync_values[k], async_values[k]), k


def test_scope_exit_joins_and_flushes():
    stream = _batches([8] * 6, seed=7)
    with engine_context(True, donate=True):
        m = _acc()
        with scan_context(4), async_context():
            for p, t in stream:
                m.update(p, t)
        # outside the scopes: everything folded, nothing in flight
        sq = m._engine._scan
        assert sq.pending == 0
        assert m._engine.stats.scan_steps_folded == 6
        value = np.asarray(m.compute())
        ref = _acc(compiled_update=True)
        for p, t in stream:
            ref.update(p, t)
        assert np.array_equal(value, np.asarray(ref.compute()))


def test_async_without_scan_is_inert():
    p, t = _batches([8])[0]
    with engine_context(True, donate=True), async_context():
        m = _acc()
        for _ in range(6):
            m.update(p, t)
        m.compute()
        st = m._engine.stats
    assert st.async_submits == 0  # no scan queue -> nothing to drain in background
    assert st.scan_dispatches == 0


def test_prefetch_stages_host_arrays():
    rng = np.random.RandomState(8)
    host_stream = [
        (rng.rand(8, NUM_CLASSES).astype(np.float32), rng.randint(0, NUM_CLASSES, 8).astype(np.int32))
        for _ in range(8)
    ]
    with engine_context(True, donate=True), scan_context(4), async_context():
        m = _acc()
        for p, t in host_stream:
            m.update(p, t)
        value = np.asarray(m.compute())
        st = m._engine.stats
        assert st.async_prefetches > 0  # numpy payloads were device_put-staged
        ref = _acc(compiled_update=True)
        for p, t in host_stream:
            ref.update(jnp.asarray(p), jnp.asarray(t))
    assert np.array_equal(value, np.asarray(ref.compute()))


def test_overlap_attributed_and_timeline_spans():
    from torchmetrics_tpu.diag.timeline import merge_timelines

    stream = _batches([8] * 16, seed=9)
    with engine_context(True, donate=True), scan_context(4), async_context(), diag_context() as rec:
        m = _acc()
        for p, t in stream:
            m.update(p, t)
            time.sleep(0.0005)  # caller forward progress the drain overlaps
        m.compute()
        st = m._engine.stats
    assert st.async_overlap_us > 0
    drains = [e for e in rec.snapshot() if e.kind == "async.drain"]
    assert drains and all("overlap_us" in e.data for e in drains)
    # (an `async.join` event only records when the observer actually WAITED —
    # with the inter-update sleep the drains usually finish first, which is
    # exactly the overlap this test proves)
    trace = merge_timelines([{"rank": 0, "events": rec.snapshot()}])
    span_names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert "async.drain" in span_names  # the overlap is VISIBLE in the merged timeline


def test_epoch_sync_overlap_note_credits_at_join():
    stream = _batches([8] * 8, seed=10)
    with engine_context(True, donate=True), scan_context(4), async_context(), diag_context() as rec:
        m = _acc()
        for p, t in stream:
            m.update(p, t)
        st = m._engine.stats
        before = st.async_overlap_us
        note_epoch_sync(st)  # what engine/epoch.py stamps after a packed sync
        m._drain_scan("test-join")
        assert st.async_overlap_us >= before
        assert any(e.kind == "async.sync.overlap" for e in rec.snapshot())


def test_strict_guard_zero_transfers_across_background_drains():
    stream = _batches([8] * 44, seed=11)
    with engine_context(True, donate=True), scan_context(8), async_context():
        m = _acc()
        for p, t in stream[:16]:  # warm outside the guard
            m.update(p, t)
        m._drain_scan("warmup")
        with diag_context(capacity=8192) as rec, transfer_guard("strict"):
            for p, t in stream[16:]:
                m.update(p, t)
            value = m.compute()  # joins + drains in-graph; value read below
        value = np.asarray(value)
        assert rec.count("transfer.host", "transfer.blocked") == 0
        assert m._engine.stats.async_dispatches > 0
    assert value.shape == ()


# ---------------------------------------------------------------- serving


def _http_get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_sidecar_scrape_rides_pause_free_path(monkeypatch):
    """Satellite: a scrape under async mode joins background work on the
    SCRAPE thread — the flush it observes rode the worker, and the event
    stream proves both the watermark and the route."""
    from torchmetrics_tpu.serve.sidecar import MetricsSidecar

    monkeypatch.setenv("TORCHMETRICS_TPU_TRACE", "1")
    stream = _batches([8] * 10, seed=12)
    try:
        with engine_context(True, donate=True), scan_context(4), async_context():
            m = _acc()
            for p, t in stream:
                m.update(p, t)
            st = m._engine.stats
            with MetricsSidecar(port=0) as sidecar:
                status, body = _http_get(sidecar.port, "/metrics")
            assert status == 200
            # the scrape observed the full watermark: every enqueued step folded
            assert st.scan_steps_folded == 10
            assert b"tm_tpu_async_dispatches_total" in body
            from torchmetrics_tpu.diag.trace import active_recorder

            rec = active_recorder()
            kinds = rec.counts
            assert kinds.get("serve.scrape.async", 0) >= 1  # the pause-free route, narrated
            m.compute()
    finally:
        monkeypatch.delenv("TORCHMETRICS_TPU_TRACE", raising=False)


def test_concurrent_scrape_snapshot_drain_stress(tmp_path):
    """Satellite stress proof: one metric under STRICT guard with concurrent
    sidecar scrapes + continuous snapshots (incl. a SIGTERM-style preemption
    flush) + background drains — byte parity with the synchronous path and 0
    host transfers recorded on the hot loop / worker."""
    from torchmetrics_tpu.parallel.elastic import ContinuousSnapshotter, SnapshotPolicy
    from torchmetrics_tpu.serve.sidecar import MetricsSidecar
    from torchmetrics_tpu.serve.snapshot import snapshot_compute

    steps = 160
    stream = _batches([8] * steps, seed=13)

    with engine_context(True, donate=True):
        ref = _acc()
        for p, t in stream:
            ref.update(p, t)
        ref_value = np.asarray(ref.compute())
        ref_states = _states(ref)

        with scan_context(8), async_context():
            m = _acc()
            # warm the executables outside the guard (compiles host-transfer free
            # is not part of the contract)
            for p, t in stream[:16]:
                m.update(p, t)
            m.reset()

            snapper = ContinuousSnapshotter(
                m, str(tmp_path), policy=SnapshotPolicy(every_updates=50), keep=2
            )
            stop = threading.Event()
            errors = []

            def scraper(port):
                while not stop.is_set():
                    try:
                        status, _ = _http_get(port, "/metrics")
                        assert status == 200
                    except Exception as exc:  # noqa: BLE001
                        errors.append(exc)
                        return
                    time.sleep(0.002)

            def snapshotter():
                while not stop.is_set():
                    try:
                        snapshot_compute(m)
                    except Exception as exc:  # noqa: BLE001
                        errors.append(exc)
                        return
                    time.sleep(0.003)

            with MetricsSidecar(port=0) as sidecar:
                threads = [
                    threading.Thread(target=scraper, args=(sidecar.port,), daemon=True),
                    threading.Thread(target=snapshotter, daemon=True),
                ]
                for th in threads:
                    th.start()
                with diag_context(capacity=16384) as rec, transfer_guard("strict"):
                    for p, t in stream:
                        m.update(p, t)
                        snapper.note_update()  # cadence flushes ride the hot thread
                    value = m.compute()
                value = np.asarray(value)
                # the SIGTERM-style preemption flush (the handler's core, without
                # killing the test process): must write a final restorable shard
                seq_before = snapper.seq
                assert snapper.preempt_flush(signal.SIGTERM) is not None
                assert snapper.seq == seq_before + 1
                stop.set()
                for th in threads:
                    th.join(timeout=10)

            assert not errors, errors[0]
            st = m._engine.stats
            # byte parity with the synchronous path, despite the observers
            assert np.array_equal(ref_value, value)
            states = _states(m)
            for s in ref_states:
                assert np.array_equal(ref_states[s], states[s]), s
            assert m._update_count == steps
            # 0 host transfers on the guarded context (hot loop + worker)
            assert rec.count("transfer.host", "transfer.blocked") == 0
            assert st.async_dispatches > 0  # drains genuinely rode the worker
            assert st.async_replayed_steps == 0  # ...and none of them failed
            assert snapper.flushes >= steps // 50 + 1
