"""Framework-integration tests (counterpart of reference
``tests/integrations/test_lightning.py``).

The reference proves metrics compose with a Lightning training loop: per-epoch reset
semantics, logging values inside steps, collections in loops, scriptability, dtype
transfer. Here the host framework is a plain flax/optax training loop — the
BASELINE.json north star requires existing ``metric.update()/.compute()`` scripts to
run unmodified inside jax training code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchmetrics_tpu import MeanMetric, MetricCollection
from torchmetrics_tpu.aggregation import SumMetric
from torchmetrics_tpu.classification import BinaryAccuracy, MulticlassAccuracy, MulticlassPrecision

NUM_EPOCHS = 3
NUM_BATCHES = 4
BATCH = 32
CLASSES = 5


def _batches(seed):
    rng = np.random.RandomState(seed)
    for _ in range(NUM_BATCHES):
        logits = rng.randn(BATCH, CLASSES).astype(np.float32)
        labels = rng.randint(0, CLASSES, BATCH)
        yield jnp.asarray(logits), jnp.asarray(labels)


def test_metric_across_epochs_with_reset():
    """Per-epoch compute + reset mirrors the reference's epoch-end semantics."""
    metric = MulticlassAccuracy(num_classes=CLASSES, average="micro")
    epoch_values = []
    for epoch in range(NUM_EPOCHS):
        golds, preds_all = [], []
        for logits, labels in _batches(epoch):
            metric.update(logits, labels)
            golds.append(np.asarray(labels))
            preds_all.append(np.asarray(logits).argmax(-1))
        val = float(metric.compute())
        want = float((np.concatenate(preds_all) == np.concatenate(golds)).mean())
        assert val == pytest.approx(want, abs=1e-6)
        epoch_values.append(val)
        metric.reset()
        assert metric._update_count == 0
    assert len(set(epoch_values)) > 1  # different epochs saw different data


def test_metric_inside_jitted_train_step():
    """The functional stages drive a jitted train step; the stateful wrapper follows."""
    from torchmetrics_tpu.functional.classification.stat_scores import (
        _multiclass_stat_scores_format,
        _multiclass_stat_scores_update,
    )

    params = jnp.zeros((CLASSES,))

    @jax.jit
    def train_step(params, metric_state, logits, labels):
        loss = jnp.mean((logits - params[None, :]) ** 2)
        p, t = _multiclass_stat_scores_format(logits, labels, top_k=1)
        tp, fp, tn, fn = _multiclass_stat_scores_update(p, t, CLASSES, 1, "micro", "global", None)
        new_state = jax.tree_util.tree_map(lambda s, d: s + d, metric_state, (tp, fp, tn, fn))
        return params - 0.1 * jax.grad(lambda q: jnp.mean((logits - q[None, :]) ** 2))(params), new_state, loss

    state = tuple(jnp.asarray(0) for _ in range(4))
    for logits, labels in _batches(0):
        params, state, loss = train_step(params, state, logits, labels)
    tp, fp, tn, fn = state
    acc = float(tp / (tp + fn))
    ref = MulticlassAccuracy(num_classes=CLASSES, average="micro")
    for logits, labels in _batches(0):
        ref.update(logits, labels)
    assert acc == pytest.approx(float(ref.compute()), abs=1e-6)


def test_collection_logging_in_loop():
    """Collections update once per step and produce the full dict each epoch."""
    coll = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=CLASSES, average="micro"),
            "prec": MulticlassPrecision(num_classes=CLASSES, average="macro"),
        }
    )
    logged = []
    for logits, labels in _batches(1):
        logged.append({k: float(v) for k, v in coll(logits, labels).items()})
    epoch = {k: float(v) for k, v in coll.compute().items()}
    assert set(epoch) == {"acc", "prec"}
    assert all(set(step) == {"acc", "prec"} for step in logged)
    coll.reset()
    for m in coll.values():
        assert m._update_count == 0


def test_loss_tracking_with_aggregation():
    """MeanMetric tracks a scalar loss stream like self.log(on_epoch=True)."""
    mean_loss = MeanMetric()
    losses = []
    for logits, labels in _batches(2):
        loss = float(jnp.mean(logits**2))
        mean_loss.update(loss)
        losses.append(loss)
    assert float(mean_loss.compute()) == pytest.approx(np.mean(losses), rel=1e-6)


def test_set_dtype_transfer():
    """set_dtype moves states like Lightning's precision plugins move modules."""
    metric = BinaryAccuracy()
    metric.update(jnp.asarray([0.1, 0.9, 0.8]), jnp.asarray([0, 1, 1]))
    metric.set_dtype(jnp.bfloat16)
    val = metric.compute()
    assert float(val) == pytest.approx(1.0)
    metric.set_dtype(jnp.float32)
    assert float(metric.compute()) == pytest.approx(1.0)


def test_state_dict_checkpoint_roundtrip_mid_training():
    """Persist mid-epoch, restore into a fresh metric, resume — value unchanged."""
    metric = SumMetric()
    metric.persistent(True)
    metric.update(jnp.asarray([1.0, 2.0]))
    ckpt = metric.state_dict()

    restored = SumMetric()
    restored.persistent(True)
    restored.load_state_dict(ckpt)
    restored.update(jnp.asarray([3.0]))

    metric.update(jnp.asarray([3.0]))
    assert float(restored.compute()) == float(metric.compute()) == 6.0
