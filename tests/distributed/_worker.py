"""Worker for the real N-process host-sync tests (launched by test_multiprocess_sync).

Each process initializes ``jax.distributed`` (gloo CPU collectives), then drives the
host/multi-process sync path — ``gather_all_tensors`` equal-shape, ragged pad/trim
with EVERY rank's shape distinct, and ``process_group`` sub-worlds — plus full
metric ``compute()`` syncs (stat-scores, Pearson's None-reduction moments, and a
retrieval metric's cat-reduced list states), mirroring the reference's gloo-pool
recipe (``tests/unittests/conftest.py:25-56``) at world sizes beyond its fixed 2.
"""

from __future__ import annotations

import os
import sys

RANK = int(sys.argv[1])
PORT = sys.argv[2]
WORLD = int(sys.argv[3]) if len(sys.argv) > 3 else 2

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = "gloo"

import jax  # noqa: E402

jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{PORT}", num_processes=WORLD, process_id=RANK, local_device_ids=[0]
)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from torchmetrics_tpu import PearsonCorrCoef  # noqa: E402
from torchmetrics_tpu.classification import MulticlassAccuracy  # noqa: E402
from torchmetrics_tpu.parallel.sync import gather_all_tensors, jit_distributed_available  # noqa: E402
from torchmetrics_tpu.retrieval import RetrievalMRR  # noqa: E402

assert jax.process_count() == WORLD, f"world did not form: {jax.process_count()}"
assert jit_distributed_available()

# --- 1. equal-shape gather -----------------------------------------------------------
x = jnp.full((3, 2), float(RANK + 1))
out = gather_all_tensors(x)
assert len(out) == WORLD and all(o.shape == (3, 2) for o in out)
for r in range(WORLD):
    np.testing.assert_allclose(np.asarray(out[r]), float(r + 1))

# --- 2. ragged gather: pad/trim branch, every rank a different shape -----------------
ragged = jnp.arange((RANK + 2) * 4, dtype=jnp.float32).reshape(RANK + 2, 4)
out = gather_all_tensors(ragged)
assert [o.shape for o in out] == [(r + 2, 4) for r in range(WORLD)]
np.testing.assert_allclose(np.asarray(out[RANK]), np.asarray(ragged))
for r in range(WORLD):  # trimmed content, not just shape
    np.testing.assert_allclose(np.asarray(out[r]), np.arange((r + 2) * 4).reshape(r + 2, 4))

# --- 3. process_group sub-worlds -----------------------------------------------------
mine = gather_all_tensors(x, group=[RANK])
assert len(mine) == 1
np.testing.assert_allclose(np.asarray(mine[0]), float(RANK + 1))
# a sub-world of all-but-the-last rank (size 3 at world 4); every rank still
# participates in the full-world collective underneath
sub = list(range(max(WORLD - 1, 2)))[:WORLD]
subbed = gather_all_tensors(ragged, group=sub)
assert [o.shape for o in subbed] == [(r + 2, 4) for r in sub]

# --- 4. metric compute() across the real world ---------------------------------------
rng = np.random.default_rng(0)  # identical stream on every rank
all_preds = rng.integers(0, 5, size=(WORLD, 32))
all_target = rng.integers(0, 5, size=(WORLD, 32))

acc = MulticlassAccuracy(num_classes=5, average="micro")
acc.update(jnp.asarray(all_preds[RANK]), jnp.asarray(all_target[RANK]))
synced_val = float(acc.compute())
golden = float(np.mean(all_preds.reshape(-1) == all_target.reshape(-1)))
np.testing.assert_allclose(synced_val, golden, atol=1e-6)

# unsync restored local state: recompute without sync gives the rank-local value
acc._to_sync = False
acc._computed = None
local_val = float(acc.compute())
local_golden = float(np.mean(all_preds[RANK] == all_target[RANK]))
np.testing.assert_allclose(local_val, local_golden, atol=1e-6)

# --- 5. None-reduction raw gather (Pearson moments folded at compute) ----------------
p = rng.normal(size=(WORLD, 40)).astype(np.float32)
t = (0.5 * p + 0.5 * rng.normal(size=(WORLD, 40))).astype(np.float32)
pearson = PearsonCorrCoef()
pearson.update(jnp.asarray(p[RANK]), jnp.asarray(t[RANK]))
synced_r = float(pearson.compute())
full = np.corrcoef(p.reshape(-1), t.reshape(-1))[0, 1]
np.testing.assert_allclose(synced_r, full, atol=1e-5)

# --- 6. cat-reduced list states: retrieval metric over rank-split queries ------------
n_q = 2  # queries per rank; global query ids stay disjoint across ranks
docs_per_q = 6
scores = rng.random(size=(WORLD, n_q * docs_per_q)).astype(np.float32)
rel = rng.integers(0, 2, size=(WORLD, n_q * docs_per_q))
rel[:, 0] = 1  # every first doc relevant: no empty-query edge here
indexes = np.repeat(np.arange(WORLD * n_q).reshape(WORLD, n_q), docs_per_q, axis=1)

mrr = RetrievalMRR()
mrr.update(jnp.asarray(scores[RANK]), jnp.asarray(rel[RANK]), indexes=jnp.asarray(indexes[RANK]))
synced_mrr = float(mrr.compute())

# host golden over the full world
rrs = []
for w in range(WORLD):
    for q in range(n_q):
        sl = slice(q * docs_per_q, (q + 1) * docs_per_q)
        order = np.argsort(-scores[w, sl], kind="stable")
        ranked_rel = rel[w, sl][order]
        first = np.flatnonzero(ranked_rel)
        rrs.append(1.0 / (first[0] + 1) if first.size else 0.0)
np.testing.assert_allclose(synced_mrr, np.mean(rrs), atol=1e-6)

# --- 7. BERTScore tokenized-tensor states ride the array gather ----------------------
from torchmetrics_tpu.text import BERTScore  # noqa: E402

_L, _D = 8, 6


def _toy_tokenizer(sents):
    ids = np.zeros((len(sents), _L), np.int32)
    mask = np.zeros((len(sents), _L), np.int32)
    for i, s in enumerate(sents):
        toks = [1] + [sum(map(ord, w)) % 997 + 3 for w in s.split()][: _L - 2] + [2]
        ids[i, : len(toks)] = toks
        mask[i, : len(toks)] = 1
    return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}


def _toy_forward(input_ids, attention_mask):
    freqs = jnp.arange(1, _D + 1, dtype=jnp.float32) * 0.1
    return jnp.sin(jnp.asarray(input_ids, jnp.float32)[:, :, None] * freqs)


sentences = [
    "the cat sat on the mat", "a dog ran in the park", "hello world again",
    "metrics ride the gather", "every rank holds a slice", "scores must agree",
    "one more pair here", "and a final one",
][: 2 * WORLD]
noisy = [s.replace("the", "a") for s in sentences]
lo, hi = RANK * 2, RANK * 2 + 2

dist_bs = BERTScore(model=_toy_forward, user_tokenizer=_toy_tokenizer, idf=True)
dist_bs.update(noisy[lo:hi], sentences[lo:hi])
synced = dist_bs.compute()

whole = BERTScore(model=_toy_forward, user_tokenizer=_toy_tokenizer, idf=True)
whole.update(noisy[: 2 * WORLD], sentences[: 2 * WORLD])
whole._to_sync = False  # rank-local single-process golden over the full corpus
golden_scores = whole.compute()
for key in ("precision", "recall", "f1"):
    got = np.asarray(synced[key])
    want = np.asarray(golden_scores[key])
    assert got.shape == want.shape == (2 * WORLD,), (key, got.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, err_msg=key)

# --- 8. ROUGE per-pair score arrays + SQuAD sum scalars sync across ranks ------------
from torchmetrics_tpu.text import ROUGEScore, SQuAD  # noqa: E402

r_preds = [s.replace("a", "the") for s in sentences]
rouge_dist = ROUGEScore(rouge_keys=("rouge1", "rougeL"))
rouge_dist.update(r_preds[lo:hi], sentences[lo:hi])
rouge_synced = rouge_dist.compute()
rouge_whole = ROUGEScore(rouge_keys=("rouge1", "rougeL"))
rouge_whole.update(r_preds[: 2 * WORLD], sentences[: 2 * WORLD])
rouge_whole._to_sync = False
rouge_golden = rouge_whole.compute()
for key in ("rouge1_fmeasure", "rougeL_fmeasure"):
    np.testing.assert_allclose(
        float(rouge_synced[key]), float(rouge_golden[key]), atol=1e-6, err_msg=key
    )

sq_preds = [{"prediction_text": s, "id": str(i)} for i, s in enumerate(r_preds)]
sq_target = [{"answers": {"answer_start": [0], "text": [s]}, "id": str(i)}
             for i, s in enumerate(sentences)]
squad_dist = SQuAD()
squad_dist.update(sq_preds[lo:hi], sq_target[lo:hi])
squad_synced = squad_dist.compute()
squad_whole = SQuAD()
squad_whole.update(sq_preds[: 2 * WORLD], sq_target[: 2 * WORLD])
squad_whole._to_sync = False
squad_golden = squad_whole.compute()
np.testing.assert_allclose(float(squad_synced["f1"]), float(squad_golden["f1"]), atol=1e-5)

print(f"RANK {RANK} PASS", flush=True)
