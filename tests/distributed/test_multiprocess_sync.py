"""Real 2-process ``jax.distributed`` execution of the host sync path.

The reference runs its distributed tests in actual 2-process gloo worlds
(``tests/unittests/conftest.py:25-56``, ``helpers/testers.py:404-421``); this is the
TPU-build equivalent: two CPU processes joined via ``jax.distributed.initialize``
(gloo collectives), driving ``parallel/sync.py``'s ``gather_all_tensors`` —
equal-shape, ragged pad/trim, ``process_group`` sub-worlds — and full metric
``compute()`` syncs with ``process_count() == 2`` (see ``_worker.py``).

The workers strip the axon site customization from PYTHONPATH: its forced backend
registration breaks multi-process world formation.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_WORKER = Path(__file__).resolve().parent / "_worker.py"
_REPO = Path(__file__).resolve().parents[2]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.timeout(300)
@pytest.mark.parametrize("world", [2, 4])
def test_n_process_host_sync(world):
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = str(_REPO)
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(rank), str(port), str(world)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=str(_REPO),
        )
        for rank in range(world)
    ]
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        outs.append(out)
    for rank, (proc, out) in enumerate(zip(procs, outs)):
        assert proc.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"RANK {rank} PASS" in out, f"rank {rank} did not pass:\n{out[-3000:]}"
