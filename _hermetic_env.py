"""Shared subprocess-environment builder for hermetic CPU-mesh runs.

Single owner of the axon-trigger prefix list: the axon sitecustomize registers its TPU
plugin whenever ``PALLAS_AXON_POOL_IPS`` is set and then forces
``jax_platforms="axon,cpu"`` over the env var; with the tunnel down that registration
can hang any jax call. Both ``bench.py`` (sync probe) and ``__graft_entry__.py``
(multichip dryrun) build their subprocess env here so the scrub list cannot drift.
"""

import os
from typing import Dict, Optional

_AXON_TRIGGER_PREFIXES = ("PALLAS_AXON", "AXON_")


def hermetic_cpu_env(n_devices: Optional[int] = None) -> Dict[str, str]:
    """A copy of ``os.environ`` pinned to a pure-CPU jax interpreter."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    for var in ("PJRT_DEVICE", "TPU_SKIP_MDS_QUERY", "PYTHONSTARTUP"):
        env.pop(var, None)
    for var in list(env):
        if var.startswith(_AXON_TRIGGER_PREFIXES):
            env.pop(var)
    return env
