"""Generate executable Example blocks for metric classes/functionals lacking one.

The reference ships a doctest Example in nearly every public module (219 modules
with ``>>>``); this tool closes the gap mechanically and HONESTLY: every example
is executed first (same platform config as the test suite: CPU, x64), its real
printed output captured, and only then spliced into the docstring — so
``tests/test_doctests.py`` keeps every generated block green.

Usage:  python scripts/gen_doctest_examples.py [--dry-run] [--only PREFIX]
"""

from __future__ import annotations

import argparse
import ast
import contextlib
import io
import os
import sys
from typing import Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

# ---------------------------------------------------------------- input blocks

BIN = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])",
    ">>> target = jnp.asarray([1, 0, 1, 1, 0, 0])",
]
MC = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([[0.25, 0.20, 0.55], [0.55, 0.05, 0.40], [0.10, 0.30, 0.60], [0.90, 0.05, 0.05]])",
    ">>> target = jnp.asarray([2, 1, 0, 0])",
]
ML = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])",
    ">>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])",
]
REG = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])",
    ">>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])",
]
REG_POS = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])",
    ">>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])",
]
REG2D = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])",
    ">>> target = jnp.asarray([[1.0, 2.5], [2.5, 4.0], [5.5, 6.5]])",
]
RETR = [
    ">>> import jax.numpy as jnp",
    ">>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])",
    ">>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])",
    ">>> target = jnp.asarray([False, False, True, False, True, False, True])",
]
IMG = [
    ">>> import jax, jax.numpy as jnp",
    ">>> key = jax.random.PRNGKey(42)",
    ">>> preds = jax.random.uniform(key, (2, 3, 16, 16))",
    ">>> target = preds * 0.75 + 0.1",
]
AUD = [
    ">>> import jax, jax.numpy as jnp",
    ">>> key = jax.random.PRNGKey(1)",
    ">>> target = jax.random.normal(key, (2, 100))",
    ">>> preds = target + 0.1 * jax.random.normal(jax.random.fold_in(key, 1), (2, 100))",
]
TXT = [
    ">>> preds = ['the cat sat on the mat', 'hello world']",
    ">>> target = ['the cat sat on a mat', 'hello there world']",
]
NOM = [
    ">>> import jax.numpy as jnp",
    ">>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 0, 1])",
    ">>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 0, 0])",
]

# per-class ctor kwargs, matched by substring (first hit wins)
CTOR_BY_NAME: List[Tuple[str, Optional[str]]] = [
    ("MulticlassFBetaScore", "beta=1.0, num_classes=3"),
    ("MultilabelFBetaScore", "beta=1.0, num_labels=3"),
    ("BinaryFBetaScore", "beta=1.0"),
    ("PrecisionAtFixedRecall", "min_recall=0.5"),
    ("RecallAtFixedPrecision", "min_precision=0.5"),
    ("SpecificityAtSensitivity", "min_sensitivity=0.5"),
    ("PrecisionRecallCurve", "thresholds=5"),
    ("ROC", "thresholds=5"),
    ("MinkowskiDistance", "p=3.0"),
    ("TweedieDevianceScore", "power=1.5"),
    ("FleissKappa", "mode='counts'"),
]

# extra kwargs for functionals, by substring of the function name
FN_KW: List[Tuple[str, str]] = [
    ("multiclass", "num_classes=3"),
    ("multilabel", "num_labels=3"),
    ("fbeta", "beta=1.0"),
    ("minkowski", "p=3.0"),
    ("tweedie", "power=1.5"),
    ("precision_at_fixed_recall", "min_recall=0.5"),
    ("recall_at_fixed_precision", "min_precision=0.5"),
    ("specificity_at_sensitivity", "min_sensitivity=0.5"),
    ("precision_recall_curve", "thresholds=5"),
    ("roc", "thresholds=5"),
]


def ctor_args(name: str, module: str) -> str:
    base = ""
    for frag, args in CTOR_BY_NAME:
        if frag in name:
            base = args or ""
            break
    parts = [base] if base else []
    joined = " ".join(parts)
    if name.startswith("Multiclass") and "num_classes" not in joined:
        parts.append("num_classes=3")
    if name.startswith("Multilabel") and "num_labels" not in joined:
        parts.append("num_labels=3")
    if ".nominal" in module and "num_classes" not in " ".join(parts) and "FleissKappa" not in name:
        parts.append("num_classes=3")
    return ", ".join(p for p in parts if p)


def fn_kwargs(name: str) -> str:
    parts = []
    for frag, kw in FN_KW:
        if frag in name and all(not p.startswith(kw.split("=")[0]) for p in parts):
            parts.append(kw)
    return ", ".join(parts)


def input_block(name: str, module: str) -> Optional[List[str]]:
    lname = name.lower()
    if ".nominal" in module:
        return NOM
    if ".retrieval" in module:
        return RETR
    if ".image" in module:
        return IMG
    if ".audio" in module:
        return AUD
    if ".text" in module:
        return TXT
    if ".regression" in module or ".pairwise" in module:
        if any(f in lname for f in ("log_error", "logerror", "percentage", "tweedie")):
            return REG_POS
        if "cosine" in lname or ".pairwise" in module:
            return REG2D
        return REG
    if ".classification" in module:
        if lname.startswith("multiclass"):
            return MC
        if lname.startswith("multilabel"):
            return ML
        if lname.startswith("binary"):
            return BIN
        return None  # task routers and legacy classes: skip
    return None


def choose_print(expr: str, val) -> Optional[Tuple[str, str]]:
    """(print_line, None) chosen by the VALUE's type; output captured later."""
    if isinstance(val, dict):
        if all(np.asarray(v).ndim == 0 for v in val.values()):
            line = f">>> print({{k: round(float(v), 4) for k, v in sorted({expr}.items())}})"
            return line, ""
        return None
    if isinstance(val, (tuple, list)):
        if 1 <= len(val) <= 4 and all(hasattr(v, "shape") for v in val):
            if all(np.asarray(v).ndim == 0 for v in val):
                line = f">>> print(tuple(round(float(v), 4) for v in {expr}))"
                return line, ""
            line = f">>> print(tuple(v.shape for v in {expr}))"
            return line, ""
        return None
    arr = np.asarray(val)
    if arr.ndim == 0:
        return f">>> print(round(float({expr}), 4))", ""
    if arr.ndim == 1 and arr.size <= 8:
        return f">>> print([round(float(x), 4) for x in {expr}])", ""
    if arr.ndim >= 1:
        return f">>> print({expr}.shape)", ""
    return None


CUSTOM: Dict[str, List[str]] = {
    "Perplexity": [
        ">>> import jax, jax.numpy as jnp",
        ">>> logits = jax.random.normal(jax.random.PRNGKey(22), (2, 8, 5))",
        ">>> target = jnp.asarray([[4, 0, 3, 3, 1, 2, 2, 0], [1, 4, 0, 2, 3, 4, 1, 0]])",
        "{IMPORT}",
        ">>> metric = {NAME}()",
        ">>> _ = metric.update(logits, target)",
        "{PRINT:metric.compute()}",
    ],
    "SQuAD": [
        ">>> preds = [{'prediction_text': 'the answer', 'id': 'q1'}]",
        ">>> target = [{'answers': {'answer_start': [0], 'text': ['the answer']}, 'id': 'q1'}]",
        "{IMPORT}",
        ">>> metric = {NAME}()",
        ">>> _ = metric.update(preds, target)",
        "{PRINT:metric.compute()}",
    ],
    "FleissKappa": [
        ">>> import jax.numpy as jnp",
        ">>> ratings = jnp.asarray([[2, 1, 0], [1, 1, 1], [0, 2, 1], [3, 0, 0]])",
        "{IMPORT}",
        ">>> metric = {NAME}(mode='counts')",
        ">>> _ = metric.update(ratings)",
        "{PRINT:metric.compute()}",
    ],
    "TotalVariation": [
        ">>> import jax, jax.numpy as jnp",
        ">>> img = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 8, 8))",
        "{IMPORT}",
        ">>> metric = {NAME}()",
        ">>> _ = metric.update(img)",
        "{PRINT:metric.compute()}",
    ],
    "MultiScaleStructuralSimilarityIndexMeasure": [
        ">>> import jax, jax.numpy as jnp",
        ">>> preds = jax.random.uniform(jax.random.PRNGKey(42), (1, 3, 192, 192))",
        ">>> target = preds * 0.75 + 0.1",
        "{IMPORT}",
        ">>> metric = {NAME}(data_range=1.0)",
        ">>> _ = metric.update(preds, target)",
        "{PRINT:metric.compute()}",
    ],
    "PermutationInvariantTraining": [
        ">>> import jax, jax.numpy as jnp",
        ">>> from torchmetrics_tpu.functional.audio import scale_invariant_signal_noise_ratio",
        ">>> key = jax.random.PRNGKey(5)",
        ">>> target = jax.random.normal(key, (2, 2, 50))",
        ">>> preds = target[:, ::-1] + 0.05 * jax.random.normal(jax.random.fold_in(key, 1), (2, 2, 50))",
        "{IMPORT}",
        ">>> metric = {NAME}(scale_invariant_signal_noise_ratio, 'max')",
        ">>> _ = metric.update(preds, target)",
        "{PRINT:metric.compute()}",
    ],
    "MeanAveragePrecision": [
        ">>> import jax.numpy as jnp",
        ">>> preds = [{'boxes': jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), 'scores': jnp.asarray([0.9]), 'labels': jnp.asarray([0])}]",
        ">>> target = [{'boxes': jnp.asarray([[12.0, 10.0, 58.0, 62.0]]), 'labels': jnp.asarray([0])}]",
        "{IMPORT}",
        ">>> metric = {NAME}()",
        ">>> _ = metric.update(preds, target)",
        "{PRINT:round(float(metric.compute()['map']), 4)}",
    ],
    "IntersectionOverUnion": [
        ">>> import jax.numpy as jnp",
        ">>> preds = [{'boxes': jnp.asarray([[10.0, 10.0, 60.0, 60.0]]), 'scores': jnp.asarray([0.9]), 'labels': jnp.asarray([0])}]",
        ">>> target = [{'boxes': jnp.asarray([[12.0, 10.0, 58.0, 62.0]]), 'labels': jnp.asarray([0])}]",
        "{IMPORT}",
        ">>> metric = {NAME}()",
        ">>> _ = metric.update(preds, target)",
        "{PRINT:metric.compute()}",
    ],
}
for _n in ("GeneralizedIntersectionOverUnion", "DistanceIntersectionOverUnion", "CompleteIntersectionOverUnion"):
    CUSTOM[_n] = CUSTOM["IntersectionOverUnion"]
for _n in ("PanopticQuality", "ModifiedPanopticQuality"):
    CUSTOM[_n] = [
        ">>> import jax.numpy as jnp",
        ">>> preds = jnp.asarray([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2]]])",
        ">>> target = jnp.asarray([[[0, 1], [0, 1], [6, 0], [7, 0], [1, 0]]])",
        "{IMPORT}",
        ">>> metric = {NAME}(things={0, 1}, stuffs={6, 7})",
        ">>> _ = metric.update(preds, target)",
        "{PRINT:metric.compute()}",
    ]


def build_class_snippet(name: str, module: str) -> Optional[List[str]]:
    short_mod = ".".join(module.split(".")[1:])
    if name in CUSTOM:
        lines = []
        for ln in CUSTOM[name]:
            if ln == "{IMPORT}":
                lines.append(f">>> from torchmetrics_tpu.{short_mod} import {name}")
            else:
                lines.append(ln.replace("{NAME}", name))
        return lines
    block = input_block(name, module)
    if block is None:
        return None
    args = ctor_args(name, module)
    lines = list(block)
    lines.append(f">>> from torchmetrics_tpu.{short_mod} import {name}")
    lines.append(f">>> metric = {name}({args})")
    if ".retrieval" in module:
        lines.append(">>> _ = metric.update(preds, target, indexes=indexes)")
    else:
        lines.append(">>> _ = metric.update(preds, target)")
    lines.append("{PRINT:metric.compute()}")
    return lines


def build_fn_snippet(name: str, module: str) -> Optional[List[str]]:
    short_mod = ".".join(module.split(".")[1:])
    block = input_block(name, module)
    if block is None:
        return None
    kwargs = fn_kwargs(name)
    call_args = "preds, target" + (", indexes" if False else "")
    if ".retrieval" in module:
        # functional retrieval metrics are single-query: no indexes argument
        pass
    lines = list(block)
    if ".retrieval" in module:
        lines = [
            ">>> import jax.numpy as jnp",
            ">>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])",
            ">>> target = jnp.asarray([False, True, False, True])",
        ]
    lines.append(f">>> from torchmetrics_tpu.{short_mod} import {name}")
    call = f"{name}({call_args}{', ' + kwargs if kwargs else ''})"
    lines.append("{PRINT:" + call + "}")
    return lines


def execute_snippet(lines: List[str]) -> Optional[List[str]]:
    """Run the example exactly as doctest would; return lines + captured output."""
    ns: Dict = {}
    final: List[str] = []
    try:
        for ln in lines:
            if ln.startswith("{PRINT:"):
                expr = ln[len("{PRINT:") : -1]
                val = eval(expr, ns)  # noqa: S307
                chosen = choose_print(expr, val)
                if chosen is None:
                    return None
                print_line, _ = chosen
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    exec(print_line[4:], ns)  # noqa: S102
                out = buf.getvalue().rstrip("\n")
                if not out or "\n" in out or len(out) > 140 or "nan" in out:
                    return None
                final.append(print_line)
                final.append(out)
            else:
                src = ln[4:]
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    exec(src, ns)  # noqa: S102
                if buf.getvalue().strip():
                    return None
                final.append(ln)
        return final
    except Exception:
        return None


# ---------------------------------------------------------------- splicing


def splice_example(path: str, obj_name: str, example_lines: List[str], kind: str) -> bool:
    with open(path) as fh:
        src = fh.read()
    tree = ast.parse(src)
    target_node = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.ClassDef, ast.FunctionDef)) and node.name == obj_name:
            target_node = node
            break
    if target_node is None or not target_node.body:
        return False
    first = target_node.body[0]
    if not (isinstance(first, ast.Expr) and isinstance(first.value, ast.Constant) and isinstance(first.value.value, str)):
        return False
    doc_lines = src.splitlines()
    indent = " " * (target_node.col_offset + 4)
    block = [f"{indent}Example:"] + [f"{indent}    {ln}" for ln in example_lines]
    start, end = first.lineno - 1, first.end_lineno - 1
    closing = doc_lines[end]
    if start == end:
        # single-line docstring: split it open
        stripped = closing.rstrip()
        assert stripped.endswith('"""') or stripped.endswith("'''")
        quote = stripped[-3:]
        head = stripped[:-3].rstrip()
        new = [head, ""] + block + [f"{indent}{quote}"]
        doc_lines[start : end + 1] = new
    else:
        insert = ["" if doc_lines[end - 1].strip() else None, *block]
        insert = [ln for ln in insert if ln is not None]
        doc_lines[end:end] = insert
    with open(path, "w") as fh:
        fh.write("\n".join(doc_lines) + ("\n" if src.endswith("\n") else ""))
    return True


def module_has_doctest(path: str) -> bool:
    with open(path) as fh:
        return ">>>" in fh.read()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument("--only", default="")
    ap.add_argument("--kind", choices=["class", "fn", "both"], default="both")
    args = ap.parse_args()

    import importlib
    import pkgutil

    import torchmetrics_tpu

    added, skipped = [], []
    for info in pkgutil.walk_packages(torchmetrics_tpu.__path__, prefix="torchmetrics_tpu."):
        if "native" in info.name or info.name.endswith("__init__"):
            continue
        if args.only and args.only not in info.name:
            continue
        try:
            mod = importlib.import_module(info.name)
        except Exception:
            continue
        path = mod.__file__
        if not path or not path.startswith(REPO) or module_has_doctest(path):
            continue
        is_functional = ".functional." in info.name
        if is_functional and args.kind == "class":
            continue
        if not is_functional and args.kind == "fn":
            continue
        names = [n for n in getattr(mod, "__all__", []) if not n.startswith("_")]
        if not names:
            names = [
                n
                for n, v in vars(mod).items()
                if not n.startswith("_") and getattr(v, "__module__", "") == info.name
            ]
        done = False
        for name in names:
            obj = getattr(mod, name, None)
            if obj is None or getattr(obj, "__module__", None) != info.name:
                continue
            if isinstance(obj, type):
                snippet = build_class_snippet(name, info.name)
            else:
                snippet = build_fn_snippet(name, info.name)
            if snippet is None:
                continue
            executed = execute_snippet(snippet)
            if executed is None:
                continue
            if args.dry_run:
                print(f"--- {info.name}.{name}")
                for ln in executed:
                    print("   ", ln)
                done = True
                break
            if splice_example(path, name, executed, "class" if isinstance(obj, type) else "fn"):
                added.append(f"{info.name}.{name}")
                done = True
                break
        if not done:
            skipped.append(info.name)

    print(f"added examples to {len(added)} modules")
    for a in added:
        print("  +", a)
    print(f"skipped {len(skipped)} modules")
    for s in skipped:
        print("  -", s)


if __name__ == "__main__":
    main()
