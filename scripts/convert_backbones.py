"""One-stop weight-conversion recipe: torch checkpoints -> TPU-ready npz/Flax dirs.

The reference downloads canonical backbones at runtime (torch-fidelity's
InceptionV3 for FID/KID/IS at ``image/fid.py:69-153``, torchvision VGG/Alex/
Squeeze for LPIPS, HF checkpoints for CLIPScore/BERTScore). This environment has
zero egress, so conversion is a USER step; this script is the whole recipe:

    # CNN trunks: torchvision / torch-fidelity .pth -> flax-variables npz
    python scripts/convert_backbones.py inception     inception_v3_google.pth  inception.npz
    python scripts/convert_backbones.py fid-inception pt_inception-2015-12-05.pth fid_inception.npz
    python scripts/convert_backbones.py vgg16         vgg16.pth      vgg16.npz
    python scripts/convert_backbones.py alexnet       alexnet.pth    alexnet.npz
    python scripts/convert_backbones.py squeezenet    squeezenet1_1.pth squeeze.npz
    # HF transformers (CLIP/BERT/...): torch hub dir -> Flax save_pretrained dir
    python scripts/convert_backbones.py clip  ./clip-vit-base-patch16  ./clip-flax
    python scripts/convert_backbones.py bert  ./roberta-large          ./roberta-flax

Then point the metric at the artifact:

    from torchmetrics_tpu.models.serialization import load_variables_npz
    from torchmetrics_tpu.image import FrechetInceptionDistance
    fid = FrechetInceptionDistance(feature=2048)  # with converted weights:
    from torchmetrics_tpu.models.inception import fid_inception_v3_extractor
    fid = FrechetInceptionDistance(
        feature=fid_inception_v3_extractor("2048", variables=load_variables_npz("fid_inception.npz")))

    BERTScore(model_name_or_path="./roberta-flax")   # offline HF loader picks the dir up
    CLIPScore(model_name_or_path="./clip-flax")

Every conversion prints the parameter count; compare it with the expected-values
table in ``docs/pages/weights.md`` to verify the artifact before trusting scores.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_torch_state_dict(path: str):
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=False)
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    if isinstance(obj, dict) and "state_dict" in obj and isinstance(obj["state_dict"], dict):
        obj = obj["state_dict"]
    return {k: v for k, v in obj.items()}


def convert_cnn(kind: str, src: str, dst: str) -> int:
    from torchmetrics_tpu.models.serialization import save_variables_npz

    state_dict = _load_torch_state_dict(src)
    if kind == "inception":
        from torchmetrics_tpu.models.inception import from_torch_state_dict as conv
    elif kind == "fid-inception":
        from torchmetrics_tpu.models.inception import from_fidelity_state_dict as conv
    elif kind == "vgg16":
        from torchmetrics_tpu.models.vgg import from_torch_state_dict as conv
    elif kind == "alexnet":
        from torchmetrics_tpu.models.alexnet import from_torch_state_dict as conv
    elif kind == "squeezenet":
        from torchmetrics_tpu.models.squeezenet import from_torch_state_dict as conv
    else:
        raise SystemExit(f"unknown CNN kind {kind}")
    variables = conv(state_dict)
    n = save_variables_npz(dst, variables)
    print(f"{kind}: wrote {dst} with {n:,} parameters")
    return n


def convert_hf(src: str, dst: str, auto_cls: str) -> None:
    """torch HF checkpoint (dir or hub id, must be cached) -> Flax save_pretrained dir."""
    import transformers

    flax_cls = getattr(transformers, auto_cls)
    model = flax_cls.from_pretrained(src, from_pt=True)
    model.save_pretrained(dst)
    try:
        tok = transformers.AutoTokenizer.from_pretrained(src)
        tok.save_pretrained(dst)
    except Exception as err:  # noqa: BLE001 — CLIP processors etc. may differ
        print(f"note: tokenizer not saved ({err}); copy it manually if needed")
    n = sum(int(p.size) for p in __import__("jax").tree_util.tree_leaves(model.params))
    print(f"wrote Flax checkpoint to {dst} with {n:,} parameters")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("kind", choices=["inception", "fid-inception", "vgg16", "alexnet", "squeezenet", "clip", "bert"])
    ap.add_argument("src", help="torch checkpoint (.pth) or HF checkpoint dir/id")
    ap.add_argument("dst", help="output .npz (CNNs) or output dir (clip/bert)")
    args = ap.parse_args()

    if args.kind in ("clip", "bert"):
        auto = "FlaxCLIPModel" if args.kind == "clip" else "FlaxAutoModel"
        convert_hf(args.src, args.dst, auto)
    else:
        convert_cnn(args.kind, args.src, args.dst)


if __name__ == "__main__":
    main()
