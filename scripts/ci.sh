#!/usr/bin/env bash
# One-command test gate (reference Makefile:22-26 analogue).
#
#   scripts/ci.sh          # CPU-mesh suite + doctests + differential + distributed worlds
#   scripts/ci.sh fast     # skip the differential sweep (reference side is slower)
#
# The conftest pins JAX to an 8-virtual-device CPU mesh, so this runs anywhere —
# no TPU needed. Prints the pass/fail/skip accounting at the end.
set -uo pipefail
cd "$(dirname "$0")/.."

ARGS=(-q -p no:cacheprovider -rs --no-header)
TARGET=(tests/)
if [[ "${1:-}" == "fast" ]]; then
  TARGET=(tests/ --ignore=tests/differential)
fi

python -m pytest "${TARGET[@]}" "${ARGS[@]}"
status=$?

echo
echo "=== gate summary ==="
if [[ $status -eq 0 ]]; then
  echo "RESULT: green (exit 0). Skips above are environment-gated (pesq/pystoi/"
  echo "canonical weights/network) — each carries its reason in the -rs report."
else
  echo "RESULT: FAILED (exit $status)"
fi
exit $status
