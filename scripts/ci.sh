#!/usr/bin/env bash
# One-command test gate (reference Makefile:22-26 analogue).
#
#   scripts/ci.sh          # CPU-mesh suite + doctests + differential + distributed worlds
#   scripts/ci.sh fast     # skip the differential sweep (reference side is slower)
#
# The conftest pins JAX to an 8-virtual-device CPU mesh, so this runs anywhere —
# no TPU needed. Prints the pass/fail/skip accounting at the end.
set -uo pipefail
cd "$(dirname "$0")/.."

ARGS=(-q -p no:cacheprovider -rs --no-header -m "not slow")
TARGET=(tests/)
if [[ "${1:-}" == "fast" ]]; then
  TARGET=(tests/ --ignore=tests/differential)
fi

python -m pytest "${TARGET[@]}" "${ARGS[@]}"
status=$?

echo
echo "=== tmlint (static invariant analysis) ==="
# The per-PR grep blocks that used to live here (engine/epoch/telemetry/
# profiling/chaos/txn/numerics/serve/scan/async/cse) are gone: their
# *numeric* proofs gate in scripts/check_counters.py below (scenario
# completeness included), and their *structural* invariants — transfer
# purity, the fail-loud env-knob contract, rider-key lockstep, counter/
# telemetry lockstep, the event taxonomy, and the cross-thread lock
# discipline — now gate STATICALLY from the source text. The committed
# baseline (tools/tmlint/baseline.json) ships empty: any finding fails.
if ! python -m tools.tmlint torchmetrics_tpu/; then
  echo "tmlint: FAILED (static invariant violation — see findings above)"
  status=1
fi

echo
echo "=== persist wiring (zero-cold-start serving) ==="
# The numeric cold-start proof gates in check_counters (coldstart scenario)
# and the durability contract gates in tmlint (TM701/TM702); this block pins
# the WIRING neither sees from one file alone: every engine compile funnel
# must record a prewarm-manifest row, and the sidecar must run the warm
# handoff — losing either silently turns prewarm into a no-op.
persist_ok=1
for f in compiled fusion epoch scan; do
  if ! grep -q '_persist\.record_compile' "torchmetrics_tpu/engine/$f.py"; then
    echo "persist: engine/$f.py lost its record_compile manifest site"
    persist_ok=0
  fi
done
if ! grep -q 'warm_start' torchmetrics_tpu/serve/sidecar.py; then
  echo "persist: serve/sidecar.py lost the warm_start handoff"
  persist_ok=0
fi
if ! grep -q 'TORCHMETRICS_TPU_PERSIST' torchmetrics_tpu/engine/config.py; then
  echo "persist: TORCHMETRICS_TPU_PERSIST missing from KNOB_REGISTRY"
  persist_ok=0
fi
if ! grep -q 'try_load_executable' torchmetrics_tpu/diag/costs.py; then
  echo "persist: diag/costs.py aot funnel lost its cache-load path"
  persist_ok=0
fi
if [[ $persist_ok -eq 1 ]]; then
  echo "persist wiring: ok"
else
  status=1
fi

echo
echo "=== federation wiring (multi-pod aggregation plane) ==="
# The numeric federation proofs gate in check_counters (federation scenario:
# parity, byte-stable membership, degraded/rejoin semantics, KLL bound) and
# the knob/event/boundary contracts gate in tmlint; this block pins the
# WIRING neither sees from one file alone: the sidecar must serve the
# versioned /state envelope, the aggregator must pull through the resilience
# tier's bounded_pull (the fault-injection boundary the churn suite plants
# on), and the KLL sketch must merge through its callable dist_reduce_fx —
# losing any of these silently turns a federation into a single-pod view.
federation_ok=1
if ! grep -q '_state_response' torchmetrics_tpu/serve/sidecar.py; then
  echo "federation: serve/sidecar.py lost the versioned /state endpoint"
  federation_ok=0
fi
if ! grep -q 'bounded_pull' torchmetrics_tpu/serve/federation.py; then
  echo "federation: serve/federation.py no longer pulls through bounded_pull"
  federation_ok=0
fi
if ! grep -q 'pack_from' torchmetrics_tpu/serve/federation.py; then
  echo "federation: serve/federation.py lost the packed-plan fold staging"
  federation_ok=0
fi
if ! grep -q 'TORCHMETRICS_TPU_FEDERATION_STALENESS_S' torchmetrics_tpu/engine/config.py; then
  echo "federation: TORCHMETRICS_TPU_FEDERATION_* knobs missing from KNOB_REGISTRY"
  federation_ok=0
fi
if ! grep -q 'dist_reduce_fx=kll_merge' torchmetrics_tpu/serve/quantile.py; then
  echo "federation: serve/quantile.py lost the callable kll_merge reduction"
  federation_ok=0
fi
if ! grep -q 'federation-ingest' torchmetrics_tpu/diag/transfer_guard.py; then
  echo "federation: the federation-ingest boundary left TRANSFER_LABELS"
  federation_ok=0
fi
if [[ $federation_ok -eq 1 ]]; then
  echo "federation wiring: ok"
else
  status=1
fi

echo
echo "=== diag dump smoke (postmortem CLI) ==="
# The one-shot observability dump (diag report + telemetry + ledger +
# provenance) must keep working as a CLI — it is the documented postmortem
# entry point, and nothing else imports it, so only this smoke would notice.
DUMP_OUT=$(mktemp)
if ! JAX_PLATFORMS=cpu python scripts/diag_dump.py --demo > "$DUMP_OUT"; then
  echo "diag dump: FAILED (postmortem CLI must exit 0 on the demo workload)"
  status=1
elif ! python - "$DUMP_OUT" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
missing = {"report", "telemetry", "ledger", "provenance", "demo"} - set(doc)
assert not missing, f"dump missing surfaces: {sorted(missing)}"
assert doc["demo"]["provenance"]["steps_folded"] > 0, "demo folded nothing"
PY
then
  echo "diag dump: FAILED (dump missing a surface — see assertion above)"
  status=1
else
  echo "diag dump: ok (all four surfaces + demo provenance present)"
fi
rm -f "$DUMP_OUT"

echo
echo "=== bench smoke (CPU) ==="
# The r05 regression class: bench.py must degrade to partial JSON with explicit
# status markers and rc=0 when no TPU exists — never die with a traceback.
BENCH_OUT=$(mktemp)
JAX_PLATFORMS=cpu python bench.py --smoke > "$BENCH_OUT"
bench_rc=$?
if [[ $bench_rc -ne 0 ]]; then
  echo "bench smoke: FAILED (rc=$bench_rc — must be 0 even without a TPU)"
  status=1
elif ! grep -q '"status"' "$BENCH_OUT" || ! grep -q '"tpu_unavailable"' "$BENCH_OUT"; then
  echo "bench smoke: FAILED (missing status markers in output)"
  status=1
else
  echo "bench smoke: ok (rc=0 + status markers; counters gate numerically in check_counters)"
fi

echo
echo "=== bench counter trend ==="
# Longitudinal view across every committed BENCH_r*.json round (informational)
# + hard failure when a key counter regresses past the newest committed
# envelope beyond the slack rules (the slow-boil regression class a single
# baseline diff cannot see).
if ! python scripts/bench_trend.py --bench-json "$BENCH_OUT"; then
  echo "bench trend: FAILED (key counter regressed past the newest envelope)"
  status=1
fi

echo
echo "=== counter regression gate (diag) ==="
# Diffs the smoke run's counters against the NEWEST committed BENCH_r*.json
# envelope (check_counters picks it automatically — a stale envelope can no
# longer be silently compared against). The engine + epoch scenarios run under
# the diag STRICT transfer guard, so this also gates the zero-host-transfer
# invariant (0 transfers recorded), uncaused warm retraces, the recorder
# overhead bound (< 2%), sentinel health (flags == 0 on clean data, the
# planted NaN detected), and the compile-time/peak-bytes ledger envelope.
if ! python scripts/check_counters.py --bench-json "$BENCH_OUT"; then
  echo "counter gate: FAILED (see violations above)"
  status=1
fi
rm -f "$BENCH_OUT"

echo
echo "=== gate summary ==="
if [[ $status -eq 0 ]]; then
  echo "RESULT: green (exit 0). Skips above are environment-gated (pesq/pystoi/"
  echo "canonical weights/network) — each carries its reason in the -rs report."
else
  echo "RESULT: FAILED (exit $status)"
fi
exit $status
