#!/usr/bin/env bash
# One-command test gate (reference Makefile:22-26 analogue).
#
#   scripts/ci.sh          # CPU-mesh suite + doctests + differential + distributed worlds
#   scripts/ci.sh fast     # skip the differential sweep (reference side is slower)
#
# The conftest pins JAX to an 8-virtual-device CPU mesh, so this runs anywhere —
# no TPU needed. Prints the pass/fail/skip accounting at the end.
set -uo pipefail
cd "$(dirname "$0")/.."

ARGS=(-q -p no:cacheprovider -rs --no-header -m "not slow")
TARGET=(tests/)
if [[ "${1:-}" == "fast" ]]; then
  TARGET=(tests/ --ignore=tests/differential)
fi

python -m pytest "${TARGET[@]}" "${ARGS[@]}"
status=$?

echo
echo "=== bench smoke (CPU) ==="
# The r05 regression class: bench.py must degrade to partial JSON with explicit
# status markers and rc=0 when no TPU exists — never die with a traceback.
BENCH_OUT=$(mktemp)
JAX_PLATFORMS=cpu python bench.py --smoke > "$BENCH_OUT"
bench_rc=$?
if [[ $bench_rc -ne 0 ]]; then
  echo "bench smoke: FAILED (rc=$bench_rc — must be 0 even without a TPU)"
  status=1
elif ! grep -q '"status"' "$BENCH_OUT" || ! grep -q '"tpu_unavailable"' "$BENCH_OUT"; then
  echo "bench smoke: FAILED (missing status markers in output)"
  status=1
elif ! grep -q '"retraces_after_warmup": 0' "$BENCH_OUT" || ! grep -q '"dispatch_reduction"' "$BENCH_OUT"; then
  echo "bench smoke: FAILED (engine counters missing from output)"
  status=1
elif ! grep -qE '"packed_collectives_per_sync": [12],' "$BENCH_OUT"; then
  # epoch engine gate: a sync must cost O(dtypes) collectives, not O(states)
  echo "bench smoke: FAILED (epoch packed sync not O(dtypes) collectives)"
  status=1
elif ! grep -q '"epoch_compute_retraces_after_warmup": 0' "$BENCH_OUT" || ! grep -q '"parity_ok": true' "$BENCH_OUT"; then
  echo "bench smoke: FAILED (epoch engine retraced after warmup or diverged from eager sync)"
  status=1
elif ! grep -q '"sentinel_nan_flagged": true' "$BENCH_OUT" || ! grep -q '"sentinel_host_transfers": 0' "$BENCH_OUT"; then
  # telemetry gate: the in-graph health sentinel must detect a planted NaN
  # with zero hot-loop host transfers under the STRICT guard
  echo "bench smoke: FAILED (sentinel did not flag the planted NaN with 0 host transfers)"
  status=1
elif ! grep -q '"ledger_executables"' "$BENCH_OUT" || ! grep -q '"ledger_compile_ms_total"' "$BENCH_OUT"; then
  echo "bench smoke: FAILED (cost/memory ledger missing from output)"
  status=1
elif ! grep -q '"straggler_rank_correct": true' "$BENCH_OUT" || ! grep -q '"sync_straggler_flags": 0' "$BENCH_OUT"; then
  # profiling gate: the planted world-2 straggler must attribute the correct
  # rank while the clean packed run stays skew-free
  echo "bench smoke: FAILED (straggler not attributed / clean run flagged a straggler)"
  status=1
elif ! grep -q '"profile_host_transfers": 0' "$BENCH_OUT" || ! grep -q '"dispatch_p99_us"' "$BENCH_OUT"; then
  echo "bench smoke: FAILED (profiled run missing p50/p99 histograms or did a host transfer)"
  status=1
elif ! grep -q '"fault_timeout_parity_ok": true' "$BENCH_OUT" \
  || ! grep -q '"degraded_rank_correct": true' "$BENCH_OUT" \
  || ! grep -q '"reshard_roundtrip_ok": true' "$BENCH_OUT" \
  || ! grep -q '"fault_host_transfers": 0' "$BENCH_OUT"; then
  # chaos smoke (fault-tolerance gate): the planted collective timeout must
  # recover by retry with parity, the planted rank-drop must fold in degraded
  # mode excluding the correct rank, the world-2 -> world-1 checkpoint reshard
  # must compute identically — all with zero unsanctioned host transfers
  echo "bench smoke: FAILED (planted-fault recovery proofs missing or degraded)"
  status=1
elif ! grep -q '"quarantined_match": true' "$BENCH_OUT" \
  || ! grep -q '"quarantine_host_transfers": 0' "$BENCH_OUT" \
  || ! grep -q '"clean_quarantined_batches": 0' "$BENCH_OUT" \
  || ! grep -q '"ladder_parity_ok": true' "$BENCH_OUT" \
  || ! grep -q '"sigterm_snapshot_ok": true' "$BENCH_OUT"; then
  # transactional-integrity smoke (engine/txn.py gate): the poisoned stream
  # must quarantine exactly the planted batches in-graph (zero host transfers,
  # byte-identical final values), the clean run must quarantine nothing, the
  # planted compile-OOM must step down the fallback ladder with parity, and a
  # SIGTERM'd run must leave a restore_latest()-able fingerprint-exact snapshot
  echo "bench smoke: FAILED (state-transaction quarantine/ladder/snapshot proofs missing or degraded)"
  status=1
elif ! grep -q '"drift_demonstrated": true' "$BENCH_OUT" \
  || ! grep -q '"compensated_ok": true' "$BENCH_OUT" \
  || ! grep -q '"numerics_host_transfers": 0' "$BENCH_OUT" \
  || ! grep -q '"drift_flagged": true' "$BENCH_OUT" \
  || ! grep -q '"precision_loss_flagged": true' "$BENCH_OUT" \
  || ! grep -q '"drift_flags_clean": 0' "$BENCH_OUT" \
  || ! grep -q '"sync_parity_ok": true' "$BENCH_OUT"; then
  # numerical-resilience smoke (engine/numerics.py gate): the 18k-step long
  # stream must drift >= 1e-3 on the naive float32 path while the compensated
  # two-sum path holds 1e-6 parity with the float64 reference — in the same
  # donated graph with zero host transfers; the drift audit + precision_loss
  # sentinel must fire on the planted run and stay silent on the clean one;
  # the world-2 packed sync must fold (value, residual) pairs with parity
  echo "bench smoke: FAILED (compensated-accumulation drift/rescue proofs missing or degraded)"
  status=1
elif ! grep -q '"serve_host_transfers": 0' "$BENCH_OUT" \
  || ! grep -q '"serve_retraces_after_warmup": 0' "$BENCH_OUT" \
  || ! grep -q '"tenant_traces": 1' "$BENCH_OUT" \
  || ! grep -q '"snapshot_nonblocking_ok": true' "$BENCH_OUT" \
  || ! grep -q '"hll_within_bound": true' "$BENCH_OUT" \
  || ! grep -q '"sketch_merge_parity_ok": true' "$BENCH_OUT" \
  || ! grep -q '"sidecar_content_type_ok": true' "$BENCH_OUT"; then
  # serving smoke (serve/ gate): the windowed streaming loop must hold 0 host
  # transfers + 0 warm retraces under the STRICT guard, 10^4 tenant slices
  # must share ONE executable signature, snapshot-compute must demonstrably
  # not block the hot loop, the HLL must hold its ±3% bound, the world-2
  # sketch merge must be bit-exact, and the sidecar must answer with the
  # 0.0.4 exposition content type
  echo "bench smoke: FAILED (serving stream/tenancy/snapshot/sketch proofs missing or degraded)"
  status=1
elif ! grep -q '"scan_dispatch_amortization_k8": 8.0' "$BENCH_OUT" \
  || ! grep -q '"scan_parity_ok": true' "$BENCH_OUT" \
  || ! grep -q '"scan_ragged_retraces_after_warmup": 0' "$BENCH_OUT" \
  || ! grep -q '"scan_host_transfers": 0' "$BENCH_OUT" \
  || ! grep -q '"scan_flush_on_observation_ok": true' "$BENCH_OUT"; then
  # multi-step scan smoke (engine/scan.py gate): K=8 drains must fold exactly
  # 8 real steps per dispatch (the counter-ratio amortization contract), stay
  # byte-identical to step-at-a-time updates with a mid-queue quarantined
  # batch + compensated accumulation on, reuse K-bucket executables across
  # ragged queue tails, flush on observation, and hold the STRICT guard
  echo "bench smoke: FAILED (multi-step scan fold/parity/flush proofs missing or degraded)"
  status=1
elif ! grep -q '"async_parity_ok": true' "$BENCH_OUT" \
  || ! grep -q '"async_overlap_ok": true' "$BENCH_OUT" \
  || ! grep -q '"async_overlap_in_timeline_ok": true' "$BENCH_OUT" \
  || ! grep -q '"async_replayed_steps": 0' "$BENCH_OUT" \
  || ! grep -q '"async_retraces_after_warmup": 0' "$BENCH_OUT" \
  || ! grep -q '"async_host_transfers": 0' "$BENCH_OUT" \
  || ! grep -q '"async_enqueue_cost_ratio"' "$BENCH_OUT"; then
  # async dispatch smoke (engine/async_dispatch.py gate): background drains
  # must stay byte-identical to the synchronous scan path (riders composed),
  # attribute real overlap (counter + merged-timeline spans), lose no payload
  # on the clean run, add no executables past the scan tier's cache, and hold
  # the STRICT guard across the worker-thread hop; the <= 1/4 enqueue-cost
  # ratio itself gates numerically in check_counters
  echo "bench smoke: FAILED (async background-drain overlap/parity/replay proofs missing or degraded)"
  status=1
elif ! grep -q '"cse_groups": 1' "$BENCH_OUT" \
  || ! grep -q '"cse_discovered_at_construction": true' "$BENCH_OUT" \
  || ! grep -q '"cse_shared_reduction_traces": 1' "$BENCH_OUT" \
  || ! grep -q '"cse_dispatches_per_step": 1.0' "$BENCH_OUT" \
  || ! grep -q '"cse_parity_ok": true' "$BENCH_OUT" \
  || ! grep -q '"cse_host_transfers": 0' "$BENCH_OUT" \
  || ! grep -q '"cse_spec_fallbacks": 0' "$BENCH_OUT"; then
  # cross-metric CSE smoke (engine/statespec.py + collections.py gate): the
  # 10-metric stat-scores family must resolve to ONE construction-time
  # compute group tracing the shared reduction once and dispatching once per
  # step, byte-identical to independent metrics with riders composed, with
  # zero host transfers and zero deprecated-convention spec fallbacks
  echo "bench smoke: FAILED (cross-metric CSE shared-reduction proofs missing or degraded)"
  status=1
else
  echo "bench smoke: ok (rc=0, status markers + engine + epoch + telemetry + profiling + chaos + txn + numerics + serve + scan + async + cse counters present)"
fi

echo
echo "=== bench counter trend ==="
# Longitudinal view across every committed BENCH_r*.json round (informational)
# + hard failure when a key counter regresses past the newest committed
# envelope beyond the slack rules (the slow-boil regression class a single
# baseline diff cannot see).
if ! python scripts/bench_trend.py --bench-json "$BENCH_OUT"; then
  echo "bench trend: FAILED (key counter regressed past the newest envelope)"
  status=1
fi

echo
echo "=== counter regression gate (diag) ==="
# Diffs the smoke run's counters against the NEWEST committed BENCH_r*.json
# envelope (check_counters picks it automatically — a stale envelope can no
# longer be silently compared against). The engine + epoch scenarios run under
# the diag STRICT transfer guard, so this also gates the zero-host-transfer
# invariant (0 transfers recorded), uncaused warm retraces, the recorder
# overhead bound (< 2%), sentinel health (flags == 0 on clean data, the
# planted NaN detected), and the compile-time/peak-bytes ledger envelope.
if ! python scripts/check_counters.py --bench-json "$BENCH_OUT"; then
  echo "counter gate: FAILED (see violations above)"
  status=1
fi
rm -f "$BENCH_OUT"

echo
echo "=== gate summary ==="
if [[ $status -eq 0 ]]; then
  echo "RESULT: green (exit 0). Skips above are environment-gated (pesq/pystoi/"
  echo "canonical weights/network) — each carries its reason in the -rs report."
else
  echo "RESULT: FAILED (exit $status)"
fi
exit $status
