"""Convert the LPIPS linear-head checkpoints to the bundled ``lpips_heads.npz``.

The LPIPS paper's learned per-layer 1x1 heads ship with the upstream project as tiny
torch checkpoints (reference ``src/torchmetrics/functional/image/lpips_models/
{alex,squeeze,vgg}.pth``, loaded at ``lpips.py:286``). This script torch-loads them and
re-serializes the raw float arrays (~6 KB total) as a single npz the JAX package can
read without torch at runtime.

Usage::

    python scripts/convert_lpips_heads.py [SRC_DIR] [DST_NPZ]

Defaults: SRC_DIR = /root/reference/src/torchmetrics/functional/image/lpips_models,
DST_NPZ = torchmetrics_tpu/functional/image/_weights/lpips_heads.npz.
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

_DEFAULT_SRC = Path("/root/reference/src/torchmetrics/functional/image/lpips_models")
_DEFAULT_DST = (
    Path(__file__).resolve().parent.parent
    / "torchmetrics_tpu"
    / "functional"
    / "image"
    / "_weights"
    / "lpips_heads.npz"
)


def convert(src_dir: Path, dst: Path) -> None:
    import torch

    out = {}
    for net in ("alex", "squeeze", "vgg"):
        sd = torch.load(src_dir / f"{net}.pth", map_location="cpu")
        for key, tensor in sd.items():
            # 'lin{i}.model.1.weight' with shape (1, C, 1, 1) -> flat (C,)
            idx = int(key.split(".")[0][len("lin") :])
            out[f"{net}_lin{idx}"] = np.asarray(tensor, dtype=np.float32).reshape(-1)
    dst.parent.mkdir(parents=True, exist_ok=True)
    np.savez(dst, **out)
    print(f"wrote {dst} ({dst.stat().st_size} bytes, {len(out)} heads)")


if __name__ == "__main__":
    src = Path(sys.argv[1]) if len(sys.argv) > 1 else _DEFAULT_SRC
    dst = Path(sys.argv[2]) if len(sys.argv) > 2 else _DEFAULT_DST
    convert(src, dst)
