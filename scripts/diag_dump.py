#!/usr/bin/env python
"""One-shot postmortem dump: diag report + telemetry + ledger + provenance.

The observability docs describe the four surfaces as Python calls
(``diag_report()``, ``telemetry_snapshot()``, ``ledger_snapshot()``,
``lineage_snapshot()``); this is the CLI entry point that prints them all as
one JSON document, so an operator staring at a crashed pod's snapshot
directory never has to open a REPL.

Modes (mutually composable surfaces, one process, one JSON doc on stdout):

  python scripts/diag_dump.py --demo
      Run a tiny self-contained workload (compiled scan + an observation)
      and dump its surfaces — the CI smoke path, and the fastest way to see
      what a healthy dump looks like.

  python scripts/diag_dump.py /path/to/snapshot_dir
      Inspect an elastic-snapshot directory (``snap-NNNNNN-rRR-of-WW.npz``
      shards from ``ContinuousSnapshotter`` / ``save_state_shard``): list
      every sequence, load the newest shards, and report state names,
      shapes, dtypes, and payload CRCs without needing the metric class.

  python scripts/diag_dump.py /path/to/snapshot_dir --metric mod:Class
      Additionally restore the newest integrity-clean sequence into a fresh
      instance of ``mod:Class`` (constructor kwargs via ``--kwargs JSON``),
      observe it through the lineage plane, and compute() — so the dump's
      ``provenance`` section carries a real watermark row for the restored
      metric alongside its computed value.

Always exits 0 on a clean dump; any failure is a loud traceback (the
fail-loud contract — a postmortem tool that guesses is worse than none).
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
from typing import Any, Dict, Optional

# runnable as `python scripts/diag_dump.py` without an installed package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _surfaces() -> Dict[str, Any]:
    """The four observability surfaces, in one dict."""
    from torchmetrics_tpu.diag import diag_report, ledger_snapshot, lineage_snapshot, telemetry_snapshot

    return {
        "report": diag_report(),
        "telemetry": telemetry_snapshot(),
        "ledger": ledger_snapshot(),
        "provenance": lineage_snapshot(),
    }


def _inspect_snapshot_dir(directory: str) -> Dict[str, Any]:
    """Raw shard inventory: sequences, shapes, dtypes, CRCs — no metric class needed."""
    import numpy as np

    from torchmetrics_tpu.parallel.elastic import list_snapshots

    sequences = list_snapshots(directory)
    out: Dict[str, Any] = {
        "directory": directory,
        "sequences": [seq for seq, _ in sequences],
        "newest": None,
    }
    if not sequences:
        return out
    seq, shard_paths = sequences[-1]
    shards = []
    for path in shard_paths:
        with np.load(path) as archive:
            states = {
                name: {"shape": list(archive[name].shape), "dtype": str(archive[name].dtype)}
                for name in archive.files
                if not name.startswith("__")
            }
            meta = {
                name.strip("_"): int(archive[name])
                for name in ("__rank__", "__world__", "__crc__", "__elastic_version__")
                if name in archive.files
            }
        shards.append({"path": path, "states": states, **meta})
    out["newest"] = {"seq": seq, "shards": shards}
    return out


def _restore_and_observe(directory: str, spec: str, kwargs_json: Optional[str]) -> Dict[str, Any]:
    """Restore the newest snapshot into ``mod:Class`` and observe it."""
    from torchmetrics_tpu.diag import observe_metric
    from torchmetrics_tpu.parallel.elastic import restore_latest

    module_name, _, class_name = spec.partition(":")
    if not module_name or not class_name:
        raise SystemExit(f"--metric must be 'module:ClassName', got {spec!r}")
    cls = getattr(importlib.import_module(module_name), class_name)
    kwargs = json.loads(kwargs_json) if kwargs_json else {}
    metric = cls(**kwargs)
    restored_seq = restore_latest(metric, directory)
    provenance = observe_metric(metric, where="postmortem")
    value = metric.compute()
    return {
        "metric": f"{module_name}:{class_name}",
        "restored_seq": restored_seq,
        "value": value,
        "provenance": provenance.as_dict() if provenance is not None else None,
    }


def _run_demo() -> Dict[str, Any]:
    """Tiny self-contained workload so the dump has something to show."""
    import jax.numpy as jnp

    from torchmetrics_tpu import MeanMetric
    from torchmetrics_tpu.diag import diag_context, observe_metric
    from torchmetrics_tpu.engine.config import engine_context
    from torchmetrics_tpu.engine.scan import scan_context

    # scan on so the lineage plane has real enqueue/fold watermarks to show
    with engine_context(True), scan_context(k=2), diag_context(capacity=512):
        metric = MeanMetric(compiled_update=True)
        for step in range(4):
            metric.update(jnp.full((8,), float(step)))
        provenance = observe_metric(metric, where="demo")
        value = metric.compute()
        body = _surfaces()
    return {
        "demo": {
            "value": value,
            "provenance": provenance.as_dict() if provenance is not None else None,
        },
        **body,
    }


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("snapshot_dir", nargs="?", help="elastic snapshot directory to inspect")
    parser.add_argument("--metric", help="module:ClassName to restore the newest snapshot into")
    parser.add_argument("--kwargs", help="JSON constructor kwargs for --metric")
    parser.add_argument("--demo", action="store_true", help="run a tiny demo workload and dump it")
    parser.add_argument("--indent", type=int, default=2, help="JSON indent (0 = compact)")
    args = parser.parse_args(argv)

    if not args.demo and not args.snapshot_dir:
        parser.error("nothing to dump: pass a snapshot_dir or --demo")

    if args.demo:
        doc = _run_demo()
    else:
        doc = {"snapshot": _inspect_snapshot_dir(args.snapshot_dir)}
        if args.metric:
            doc["restored"] = _restore_and_observe(args.snapshot_dir, args.metric, args.kwargs)
        doc.update(_surfaces())

    json.dump(doc, sys.stdout, indent=args.indent or None, sort_keys=True, default=str)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
