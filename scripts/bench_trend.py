#!/usr/bin/env python3
"""Counter trajectory across committed bench rounds, plus a key-counter gate.

``check_counters.py`` answers "did THIS run regress against the newest
envelope?"; this script answers the longitudinal question — *how has each key
counter moved across every committed round?* — and catches the slow-boil
class of regression a single-baseline diff cannot see (a counter creeping up
one "within-slack" notch per PR).

Reads every ``BENCH_r*.json`` in the repo root in round order and prints one
trajectory table: dispatches/step, collectives/sync, metadata gathers/sync,
retraces after warmup, recorder & profiler overhead %, compile_ms, quarantined
batches and fallback-ladder retries. Counters a round predates print as ``-``
(older envelopes legitimately lack newer fields — including whole scenarios
and ``"extras": null`` rounds from before the counter era).

With ``--bench-json`` (a fresh ``bench.py --smoke`` output) the script also
gates: each KEY counter of the fresh run must not regress past the newest
committed baseline beyond the existing slack rules (count-shaped counters:
no worse than the baseline; machine-dependent envelopes: within 2x). Exit 0 =
informational print or all-green gate; 1 = a key counter regressed; 2 = no
rounds found / unreadable input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (scenario, counter, gate) — gate "max" = fresh <= baseline, "slack" = fresh
# <= 2x baseline, None = trajectory display only
_TRACKED = (
    ("engine", "fused_dispatches_per_step", "max"),
    ("engine", "per_metric_dispatches_per_step", None),
    ("engine", "retraces_after_warmup", "max"),
    ("engine", "eager_fallbacks", "max"),
    ("epoch", "packed_collectives_per_sync", "max"),
    ("epoch", "packed_metadata_gathers_per_sync", "max"),
    ("epoch", "epoch_compute_retraces_after_warmup", "max"),
    ("engine", "recorder_overhead_pct", "slack"),
    ("engine", "profiler_overhead_pct", "slack"),
    ("engine", "ledger_compile_ms_total", "slack"),
    # transactional layer (engine/txn.py, PR 7): quarantine + fallback ladder.
    # quarantined_batches tracks the PLANTED poison count (exactness is
    # check_counters' job); the clean-run and host-transfer counters gate.
    ("txn", "quarantined_batches", None),
    ("txn", "ladder_retries", None),
    ("txn", "quarantine_host_transfers", "max"),
    ("txn", "clean_quarantined_batches", "max"),
    # numerics layer (engine/numerics.py, PR 8): compensated accumulation.
    # the rel-err pair is the drift-vs-rescue evidence (display; the 1e-3/1e-6
    # thresholds gate in check_counters); transfers/retraces/clean-flags gate.
    ("numerics", "naive_rel_err", None),
    ("numerics", "compensated_rel_err", None),
    ("numerics", "drift_flags_planted", None),
    ("numerics", "numerics_host_transfers", "max"),
    ("numerics", "numerics_retraces_after_warmup", "max"),
    ("numerics", "drift_flags_clean", "max"),
    # multi-step scan dispatch (engine/scan.py, PR 10): amortization factors
    # are display (machine-dependent ratios; the >= 4x floor gates in
    # check_counters); steps-folded tracks adoption, transfers/retraces gate.
    ("scan", "scan_dispatch_amortization_k8", None),
    ("scan", "scan_amortization_k8", None),
    ("scan", "scan_amortization_k32", None),
    ("scan", "scan_steps_folded", None),
    ("scan", "scan_host_transfers", "max"),
    ("scan", "scan_ragged_retraces_after_warmup", "max"),
    # async pipelined dispatch (engine/async_dispatch.py, PR 13): the gated
    # enqueue-cost ratio and absolute latencies are display (check_counters
    # owns the <= 1/4 bound); transfers/retraces/replays must never creep.
    ("async", "async_enqueue_cost_ratio", None),
    ("async", "async_enqueue_p50_us", None),
    ("async", "async_overlap_us", None),
    ("async", "async_host_transfers", "max"),
    ("async", "async_retraces_after_warmup", "max"),
    ("async", "async_replayed_steps", "max"),
    # serving layer (serve/, PR 9): streaming-loop timing is display (machine-
    # dependent); transfers/retraces/executable-sharing and the HLL error gate.
    ("serve", "windowed_us_per_step", None),
    ("serve", "eager_rewindow_us_per_step", None),
    ("serve", "hll_rel_err", None),
    ("serve", "serve_host_transfers", "max"),
    ("serve", "serve_retraces_after_warmup", "max"),
    ("serve", "tenant_traces", "max"),
    ("serve", "tenant_host_transfers", "max"),
    # federated aggregation plane (serve/federation.py + quantile.py, PR 18):
    # fold latency and the KLL rank errors are trajectory evidence (machine-
    # dependent; check_counters owns the parity/degraded/bound gates); host
    # transfers outside the sanctioned boundaries must never creep above zero.
    ("federation", "federation_fold_ms", None),
    ("federation", "kll_rank_err_p50", None),
    ("federation", "kll_rank_err_p99", None),
    ("federation", "federation_host_transfers", "max"),
    # fleet observability plane (serve/fleet.py + diag/slo.py, PR 19): merge
    # latency and the merged-p99 relative error are trajectory evidence
    # (check_counters owns the bound/breach/recovery gates); host transfers
    # in the envelope cycle and SLO breach counts must never creep.
    ("fleet", "fleet_merge_ms", None),
    ("fleet", "fleet_p99_rel_err", None),
    ("fleet", "fleet_host_transfers", "max"),
    ("fleet", "slo_breaches", None),
    # value provenance & freshness plane (diag/lineage.py, PR 20): record /
    # span / mid-stream-staleness volumes are trajectory evidence (check_
    # counters owns the watermark/coverage/breach/off-identity gates); host
    # transfers and warm retraces on the provenance-bearing STRICT hot loop
    # must never creep above zero.
    ("lineage", "lineage_records", None),
    ("lineage", "lineage_spans", None),
    ("lineage", "lineage_staleness_mid", None),
    ("lineage", "lineage_host_transfers", "max"),
    ("lineage", "lineage_retraces_after_warmup", "max"),
    # cross-metric CSE (engine/statespec.py + collections.py, PR 11): the
    # speedup and footprint fraction are trajectory evidence (check_counters
    # gates the exact counter envelope); traces/dispatches/transfers and the
    # deprecated-convention fallback count must never creep.
    ("cse", "cse_speedup_vs_unfused", None),
    ("cse", "cse_footprint_fraction", None),
    ("cse", "cse_shared_reduction_traces", "max"),
    ("cse", "cse_dispatches_per_step", "max"),
    ("cse", "cse_host_transfers", "max"),
    ("cse", "cse_retraces_after_warmup", "max"),
    ("cse", "cse_spec_fallbacks", "max"),
    # heavy-metric in-graph kernels (PR 15): per-step timings and the sharded
    # footprint are trajectory evidence (machine-dependent; check_counters
    # owns the parity/single-graph gates); transfers/retraces and the clean-
    # run host-fallback count must never creep.
    ("heavy", "fid_us_per_step", None),
    ("heavy", "map_us_per_step", None),
    ("heavy", "fid_sharded_footprint_fraction", None),
    ("heavy", "fid_host_transfers", "max"),
    ("heavy", "fid_retraces_after_warmup", "max"),
    ("heavy", "map_host_transfers", "max"),
    ("heavy", "map_retraces_after_warmup", "max"),
    ("heavy", "bert_warm_retraces", "max"),
    ("heavy", "fid_host_eighs_clean", "max"),
    # zero-cold-start serving (PR 17): the warm-over-cold TTFD fraction and
    # absolute deserialize cost are trajectory evidence (machine-dependent;
    # check_counters owns the <= 10% gate); envelope rejects and host
    # transfers on the load path must never creep above zero.
    ("coldstart", "coldstart_warm_ttfd_frac", None),
    ("coldstart", "coldstart_warm_prewarm_ms", None),
    ("coldstart", "warm_deserialize_ms", None),
    ("coldstart", "persist_hits", None),
    ("coldstart", "coldstart_envelope_rejects", "max"),
    ("coldstart", "coldstart_host_transfers", "max"),
)

#: the multi-chip evidence trajectory (MULTICHIP_r*.json, PR 12 onward): the
#: sharding block lives at the file's top level ("sharding"), unlike the
#: BENCH rounds' "extras" envelope. Counters a round predates print as "-"
#: (pre-sharding rounds are raw runner transcripts with no counter block).
#: Gates compare the FRESH run's sharding scenario against the newest
#: committed multi-chip round — without this, the sharding trajectory was
#: invisible to the trend gate entirely.
_MULTICHIP_TRACKED = (
    ("sharding", "shard_states", "max"),  # placements must not silently shrink... or grow unbounded
    ("sharding", "psum_syncs", None),
    ("sharding", "gather_skipped", None),
    ("sharding", "sharding_footprint_fraction", "max"),  # per-device bytes ~1/mesh
    ("sharding", "sharding_host_transfers", "max"),
    ("sharding", "sharding_retraces_after_warmup", "max"),
    ("sharding", "million_class_update_executables", "max"),  # ONE SPMD graph
    ("sharding", "million_class_us_per_step", None),  # machine-dependent: display
    # 2-D (data, state) mesh trajectory (PR 16, MULTICHIP_r07 onward): the
    # in-graph epoch sync must STAY at zero host collectives / zero warm
    # retraces; the informational rows show how much exchange traffic rides
    # in-graph per round
    ("multichip_2d", "sync_collectives", "max"),  # zero host collectives, forever
    ("multichip_2d", "sync_metadata_gathers", "max"),
    ("multichip_2d", "ingraph_syncs", None),
    ("multichip_2d", "psum_syncs", None),
    ("multichip_2d", "sync_noop_plans", None),
    ("multichip_2d", "ingraph_retraces_warm", "max"),
    ("multichip_2d", "ingraph_host_transfers", "max"),
)

_TOL = 1e-6


def rounds(repo: str = REPO):
    """[(round_number, path)] for every committed BENCH_r*.json, in order."""
    found = []
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        match = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(path))
        if match:
            found.append((int(match.group(1)), path))
    return sorted(found)


def multichip_rounds(repo: str = REPO):
    """[(round_number, path)] for every committed MULTICHIP_r*.json, in order."""
    found = []
    for path in glob.glob(os.path.join(repo, "MULTICHIP_r*.json")):
        match = re.fullmatch(r"MULTICHIP_r(\d+)\.json", os.path.basename(path))
        if match:
            found.append((int(match.group(1)), path))
    return sorted(found)


def _counter(payload: dict, scenario: str, counter: str):
    # older rounds predate whole scenarios and may carry ``"extras": null`` or
    # a non-dict scenario slot (a tpu_unavailable status marker): every level
    # of the walk must tolerate that, not KeyError/AttributeError on it
    extras = payload.get("extras")
    if not isinstance(extras, dict):
        return None
    block = extras.get(scenario)
    if not isinstance(block, dict):
        return None
    return block.get(counter)


def _fmt(value) -> str:
    if value is None:
        return "-"
    if isinstance(value, bool):
        return str(value).lower()
    if isinstance(value, float):
        return f"{value:g}"
    return str(value)


def _multichip_counter(payload: dict, scenario: str, counter: str):
    # MULTICHIP rounds carry the scenario block at top level (no "extras"
    # envelope); pre-sharding rounds are raw runner transcripts — tolerate both
    block = payload.get(scenario)
    if not isinstance(block, dict):
        return None
    return block.get(counter)


def print_trajectory(history) -> None:
    names = [f"{s}.{c}" for s, c, _ in _TRACKED]
    name_w = max(len(n) for n in names)
    cols = [f"r{num:02d}" for num, _ in history]
    col_w = max(10, max((len(c) for c in cols), default=3))
    print(f"  {'counter':<{name_w}}  " + "  ".join(f"{c:>{col_w}}" for c in cols))
    for (scenario, counter, _), name in zip(_TRACKED, names):
        cells = [_fmt(_counter(p, scenario, counter)) for _, p in history]
        print(f"  {name:<{name_w}}  " + "  ".join(f"{c:>{col_w}}" for c in cells))


def print_multichip_trajectory(history) -> None:
    names = [f"{s}.{c}" for s, c, _ in _MULTICHIP_TRACKED]
    name_w = max(len(n) for n in names)
    cols = [f"r{num:02d}" for num, _ in history]
    col_w = max(10, max((len(c) for c in cols), default=3))
    print(f"  {'counter':<{name_w}}  " + "  ".join(f"{c:>{col_w}}" for c in cols))
    for (scenario, counter, _), name in zip(_MULTICHIP_TRACKED, names):
        cells = [_fmt(_multichip_counter(p, scenario, counter)) for _, p in history]
        print(f"  {name:<{name_w}}  " + "  ".join(f"{c:>{col_w}}" for c in cells))


def gate(fresh: dict, baseline: dict, baseline_name: str, multichip=None) -> int:
    failures = []
    for scenario, counter, kind in _TRACKED:
        if kind is None:
            continue
        got = _counter(fresh, scenario, counter)
        base = _counter(baseline, scenario, counter)
        if got is None or base is None:
            continue  # check_counters owns missing-field handling
        limit = 2.0 * float(base) if kind == "slack" else float(base)
        if float(got) > limit + _TOL:
            failures.append(
                f"{scenario}.{counter}: {got} regressed past the {baseline_name}"
                f" envelope ({'2x ' if kind == 'slack' else ''}{base})"
            )
    if multichip is not None:
        mc_name, mc_payload = multichip
        for scenario, counter, kind in _MULTICHIP_TRACKED:
            if kind is None:
                continue
            # the fresh run's sharding block rides the BENCH extras envelope;
            # the committed multi-chip evidence holds it top-level
            got = _counter(fresh, scenario, counter)
            base = _multichip_counter(mc_payload, scenario, counter)
            if got is None or base is None:
                continue
            limit = 2.0 * float(base) if kind == "slack" else float(base)
            if float(got) > limit + _TOL:
                failures.append(
                    f"{scenario}.{counter}: {got} regressed past the {mc_name}"
                    f" multichip envelope ({'2x ' if kind == 'slack' else ''}{base})"
                )
    if failures:
        print("\nbench trend gate: FAILED")
        for failure in failures:
            print(f"  - {failure}")
        return 1
    print(f"\nbench trend gate: ok (key counters hold the {baseline_name} envelope)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--bench-json", default=None,
                        help="fresh bench output to gate against the newest committed round"
                             " (omitted = print the trajectory only)")
    args = parser.parse_args(argv)

    history = []
    for num, path in rounds():
        try:
            with open(path) as fh:
                history.append((num, json.load(fh)))
        except (OSError, json.JSONDecodeError) as err:
            print(f"bench trend: skipping unreadable {os.path.basename(path)}: {err}")
    if not history:
        print("bench trend: no BENCH_r*.json rounds found")
        return 2

    print(f"bench counter trajectory over {len(history)} committed rounds:")
    print_trajectory(history)

    mc_history = []
    for num, path in multichip_rounds():
        try:
            with open(path) as fh:
                mc_history.append((num, json.load(fh)))
        except (OSError, json.JSONDecodeError) as err:
            print(f"bench trend: skipping unreadable {os.path.basename(path)}: {err}")
    if mc_history:
        print(f"\nmulti-chip counter trajectory over {len(mc_history)} committed rounds:")
        print_multichip_trajectory(mc_history)

    if args.bench_json is None:
        return 0
    try:
        with open(args.bench_json) as fh:
            fresh = json.load(fh)
    except (OSError, json.JSONDecodeError) as err:
        print(f"bench trend: cannot read --bench-json: {err}")
        return 2
    newest_num, newest = history[-1]
    multichip = None
    if mc_history:
        mc_num, mc_payload = mc_history[-1]
        multichip = (f"MULTICHIP_r{mc_num:02d}", mc_payload)
    return gate(fresh, newest, f"BENCH_r{newest_num:02d}", multichip=multichip)


if __name__ == "__main__":
    sys.exit(main())
