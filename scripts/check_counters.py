#!/usr/bin/env python3
"""Counter-regression gate: diff a fresh ``bench.py --smoke`` against the envelope.

The engine's perf claims are recorded counters, not timings — dispatches per
step, collectives per sync, retraces after warmup, host transfers. Timings vary
with the machine; the counters must not. This gate re-runs the smoke bench (or
reads an existing output via ``--bench-json``), extracts the counter envelope,
and fails CI when any counter regresses past the committed baseline
(``BENCH_r07.json`` by default) or violates an absolute invariant:

- ``fused_dispatches_per_step``   <= baseline (one dispatch per collection step)
- ``retraces_after_warmup``       <= baseline (0: warm loop never recompiles)
- ``packed_collectives_per_sync`` <= baseline (O(dtypes), not O(states))
- ``packed_metadata_gathers_per_sync`` <= baseline
- ``epoch_compute_retraces_after_warmup`` <= baseline (0)
- ``parity_ok``                   is true (packed sync == eager sync values)
- ``host_transfers`` / ``epoch_host_transfers`` == 0 — the engine + epoch
  scenarios run under the diag STRICT transfer guard; any unsanctioned
  device→host readback in the hot loop either raises (failing the scenario)
  or lands in these counters
- ``retraces_uncaused`` / ``epoch_retraces_uncaused`` == 0 — every warm-loop
  retrace in the flight recorder must carry an attributed cause
- ``recorder_overhead_pct``       < 2.0 — the flight recorder's bound on the
  engine scenario (per-event record cost x events/step vs step time)
- ``sentinel_flags`` == 0 and ``sentinel_host_transfers`` == 0 — the
  sentinel-enabled run stays healthy on clean data AND does no hot-loop host
  transfer; ``sentinel_nan_flagged`` must be true (a planted NaN IS detected)
- ``ledger_executables`` truthy and the compile-time / peak-bytes envelope
  (``ledger_compile_ms_total``, ``ledger_peak_bytes_max``) within 2x of the
  committed baseline — compile wall-time is machine-dependent, so its gate is
  a runaway detector, not a tight bound
- ``profiler_overhead_pct`` < 2.0 — the sampled-probe bound on the profiled
  engine scenario (mean blocking wait x 1/every_n vs step time, analytic like
  the recorder bound); ``profile_probes`` truthy (sampling actually engaged)
  and ``profile_host_transfers`` == 0 (probes ride the sanctioned boundary)
- ``telemetry_histogram_series`` truthy — the Prometheus export carries the
  latency histogram families (``_bucket``/``_sum``/``_count``)
- ``sync_straggler_flags`` == 0 on the CLEAN epoch run, while the
  planted-straggler run must flag (``straggler_flagged``) the CORRECT rank
  (``straggler_rank_correct``) with zero unsanctioned transfers
- transactional-integrity proofs (``engine/txn.py`` + ``parallel/elastic.py``):
  the poisoned-stream run quarantines EXACTLY the planted batch count
  (``quarantined_batches`` == ``quarantine_planted``, ``quarantined_match``)
  with byte-identical final values (``parity_ok``), zero hot-loop host
  transfers (``quarantine_host_transfers`` == 0) and zero warm retraces (the
  admission prelude lives inside the already-compiled step); the CLEAN run
  quarantines nothing (``clean_quarantined_batches`` == 0); the planted
  compile-OOM steps down the fallback ladder with parity (``ladder_retries``
  truthy, ``ladder_parity_ok``); SIGTERM mid-run leaves a restorable
  last-good snapshot whose ``restore_latest()`` fingerprint matches on every
  rank (``sigterm_snapshot_ok``)
- fault-tolerance proofs (``parallel/resilience.py`` + ``parallel/faults.py``):
  the planted collective timeout recovers by bounded retry with full parity
  (``fault_timeout_retries`` truthy, ``fault_timeout_parity_ok``), the planted
  rank-drop folds in degraded mode excluding exactly the dead rank
  (``degraded_rank_correct``, ``degraded_parity_ok``), the world-2 -> world-1
  checkpoint-reshard round-trip computes identically
  (``reshard_roundtrip_ok``), the CLEAN run pays nothing
  (``sync_degraded_folds`` == 0, ``sync_retries_clean`` == 0), and the whole
  chaos block does zero unsanctioned host transfers
  (``fault_host_transfers`` == 0)
- multi-step scan proofs (``engine/scan.py``): the queued K-step drain
  amortizes dispatch ≥4x at K=8 — gated on the machine-independent COUNTER
  ratio (``scan_dispatch_amortization_k8`` = steps folded per executed
  dispatch, 8.0 on an aligned stream), with the measured wall-clock ratio
  (``scan_amortization_k8``, typically ~4.2x on CPU) exported as evidence
  and floored at 2x as a regression tripwire (XLA CPU exec jitter for these
  micro executables swings timing ratios ±15% even on an idle machine, so
  the timing is evidence, not the contract — the repo's counters-not-timings
  philosophy) — stays byte-identical to step-at-a-time updates
  with a mid-queue quarantined batch and compensated accumulation on
  (``scan_parity_ok``, ``scan_quarantined_batches`` == planted), reuses
  K-bucket executables across ragged queue tails
  (``scan_ragged_retraces_after_warmup`` == 0), renders one ``update.scan``
  event per drain, flushes on observation, and holds the STRICT guard
  (``scan_host_transfers`` == 0); on a TPU-less run the micro fallback must
  additionally prove NO gated scenario was skipped
  (``micro_fallback.scenarios_missing`` empty)
- async pipelined dispatch proofs (``engine/async_dispatch.py``): with the
  double-buffered background drain on, the caller-side p50 enqueue cost is
  ≤ 1/4 of the synchronous K=8 scan per-step cost — gated on the PAIRED
  per-window ratio (``async_enqueue_cost_ratio``; the absolute µs figures are
  machine-dependent and export as slack tripwires) — drains genuinely execute
  off the caller (``async_dispatches``) with the overlap attributed both as
  ``async_overlap_us`` and as worker-track ``async.drain`` spans in the
  PR-5 merged timeline (``async_overlap_in_timeline_ok``), state stays
  byte-identical to the synchronous path with a mid-queue quarantined batch
  and compensated sums composed (``async_parity_ok``), the clean run loses
  no payload to a worker failure (``async_replayed_steps`` == 0), the async
  tier adds NO new executables (``async_retraces_after_warmup`` == 0 — it
  reuses the scan tier's cache), and the STRICT transfer guard, propagated
  onto the worker thread via the submit context, records 0 transfers
  (``async_host_transfers`` == 0)
- cross-metric CSE proofs (``engine/statespec.py`` + ``collections.py``): the
  10-metric stat-scores-family collection resolves to ONE compute group at
  CONSTRUCTION (``cse_groups`` == 1, ``cse_discovered_at_construction``),
  traces the shared TP/FP/TN/FN reduction exactly once
  (``cse_shared_reduction_traces`` == 1), runs one dispatch per step
  (``cse_dispatches_per_step`` == 1) with zero fallbacks/warm retraces, holds
  ~1/N unique state bytes (``cse_footprint_fraction`` <= 0.2), stays
  byte-identical to independently-computed metrics with quarantine + scan
  riders composed on the shared state (``cse_parity_ok``,
  ``cse_quarantined_batches`` == planted), does zero host transfers under the
  STRICT guard, and resolves every in-tree packed/bucketing/compensation role
  from the StateSpec registry (``cse_spec_fallbacks`` == 0)
- SPMD sharded-state proofs (``parallel/sharding.py``): class-axis states on
  a >= 2-device mesh are born distributed (``shard_states``), compute
  bit-identically to the replicated path (``sharding_parity_ok``), hold
  ~1/mesh bytes per device (``sharding_footprint_fraction``), skip the packed
  host gather in favour of in-graph psum (``gather_skipped``/``psum_syncs``,
  ``sync_value_global_ok``), run the million-class hot loop as ONE SPMD
  executable with zero host transfers and zero warm retraces
  (``million_class_single_graph_ok``), and survive clone/pickle/state_dict/
  reshard plus the K=8 scan drain (``lifecycle_roundtrip_ok``,
  ``scan_compat_ok``)
- numerical-resilience proofs (``engine/numerics.py``): the 18k-step
  long stream drifts ≥1e-3 on the naive float32 path
  (``drift_demonstrated``) while the compensated two-sum path stays within
  1e-6 of the float64 reference (``compensated_ok``) — in the SAME donated
  executable, zero host transfers, zero warm retraces; the sampled drift
  audit is byte-inert on unsampled steps (``probe_parity_ok``), fires on the
  planted run (``drift_flagged``, ``precision_loss_flagged``) and stays
  silent on the clean one (``drift_flags_clean`` == 0,
  ``clean_sentinel_flags`` == 0); the world-2 packed sync folds (value,
  residual) pairs in ≤2 collectives with 1e-6 parity (``sync_parity_ok``)

The baseline defaults to the NEWEST ``BENCH_r*.json`` in the repo root (pass
``--baseline`` to pin one) — a stale envelope can no longer be compared
against silently. Counters ABSENT from an older baseline fall back to their
absolute bound, so the gate tightens automatically as the envelope gains
fields. Exit code 0 = all green; 1 = regression (each violation printed);
2 = bench run itself broke.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (scenario, counter, kind, absolute_bound)
#   kind "max":   fresh <= max(baseline, absolute)    — counted regressions
#   kind "abs":   fresh <= absolute                   — invariants, baseline-independent
#   kind "slack": fresh <= max(2 x baseline, absolute) — machine-dependent envelopes
#   kind "true":  fresh must be truthy
#   kind "min":   fresh >= absolute                   — improvement floors (amortization)
_CHECKS = (
    ("engine", "fused_dispatches_per_step", "max", 1.0),
    ("engine", "retraces_after_warmup", "max", 0),
    ("engine", "eager_fallbacks", "max", 0),
    ("engine", "host_transfers", "abs", 0),
    ("engine", "retraces_uncaused", "abs", 0),
    ("engine", "recorder_overhead_pct", "abs", 2.0),
    ("engine", "sentinel_flags", "abs", 0),
    ("engine", "sentinel_nan_flagged", "true", None),
    ("engine", "sentinel_host_transfers", "abs", 0),
    ("engine", "ledger_executables", "true", None),
    ("engine", "telemetry_prometheus_lines", "true", None),
    ("engine", "telemetry_histogram_series", "true", None),
    ("engine", "ledger_compile_ms_total", "slack", 60000.0),
    ("engine", "ledger_peak_bytes_max", "slack", 1 << 28),
    ("engine", "profile_probes", "true", None),
    ("engine", "profile_host_transfers", "abs", 0),
    ("engine", "profiler_overhead_pct", "abs", 2.0),
    ("epoch", "packed_collectives_per_sync", "max", 2),
    ("epoch", "packed_metadata_gathers_per_sync", "max", 1),
    ("epoch", "epoch_compute_retraces_after_warmup", "max", 0),
    ("epoch", "parity_ok", "true", None),
    ("epoch", "epoch_host_transfers", "abs", 0),
    ("epoch", "epoch_retraces_uncaused", "abs", 0),
    ("epoch", "sync_straggler_flags", "abs", 0),
    ("epoch", "straggler_flagged", "true", None),
    ("epoch", "straggler_rank_correct", "true", None),
    ("epoch", "straggler_host_transfers", "abs", 0),
    # fault-tolerance gates (parallel/resilience.py + faults.py, PR 6): the
    # planted chaos scenarios must RECOVER — and the clean run must not pay
    ("epoch", "sync_degraded_folds", "abs", 0),  # clean guarded run never degrades
    ("epoch", "sync_retries_clean", "abs", 0),  # ...nor spends retries
    ("epoch", "fault_timeout_retries", "true", None),  # planted timeout DID retry
    ("epoch", "fault_timeout_degraded_folds", "abs", 0),  # ...and retry sufficed
    ("epoch", "fault_timeout_parity_ok", "true", None),  # recovered with full parity
    ("epoch", "degraded_folds", "true", None),  # planted rank-drop DID degrade
    ("epoch", "degraded_rank_correct", "true", None),  # ...excluding the right rank
    ("epoch", "degraded_parity_ok", "true", None),  # survivor fold matches
    ("epoch", "reshard_roundtrip_ok", "true", None),  # world-2 -> world-1 identical compute
    ("epoch", "fault_host_transfers", "abs", 0),  # chaos ran under the STRICT guard
    # transactional-integrity gates (engine/txn.py + parallel/elastic.py, PR 7):
    # "eqfield" compares two counters of the SAME fresh run — exactness, not an
    # envelope (the planted poison count is the run's own ground truth)
    ("txn", "quarantined_batches", "eqfield", "quarantine_planted"),
    ("txn", "quarantined_match", "true", None),  # ...and every fused member agrees
    ("txn", "parity_ok", "true", None),  # quarantined == clean-skip, byte-identical
    ("txn", "quarantine_host_transfers", "abs", 0),  # flag never read in the hot loop
    ("txn", "quarantine_retraces_after_warmup", "abs", 0),  # prelude doesn't retrace
    ("txn", "quarantine_retraces_uncaused", "abs", 0),
    ("txn", "clean_quarantined_batches", "abs", 0),  # healthy data pays nothing
    ("txn", "ladder_retries", "true", None),  # planted OOM DID step down a bucket
    ("txn", "ladder_parity_ok", "true", None),  # ...and the chunked step matches
    ("txn", "ladder_host_transfers", "abs", 0),
    ("txn", "sigterm_snapshot_ok", "true", None),  # restore_latest fingerprint parity
    # numerical-resilience gates (engine/numerics.py, PR 8): the long stream
    # must PROVE the drift (naive ≥1e-3 off the float64 reference) AND the
    # rescue (compensated ≤1e-6), with the audit machinery firing only when
    # planted — all under the STRICT transfer guard, zero warm retraces
    ("numerics", "drift_demonstrated", "true", None),  # naive float32 ≥1e-3 adrift
    ("numerics", "compensated_ok", "true", None),  # two-sum path ≤1e-6 of float64
    ("numerics", "numerics_host_transfers", "abs", 0),  # strict guard held
    ("numerics", "numerics_retraces_after_warmup", "abs", 0),  # two-sum lives in-graph
    ("numerics", "numerics_retraces_uncaused", "abs", 0),
    ("numerics", "probe_parity_ok", "true", None),  # unsampled steps byte-identical
    ("numerics", "drift_flagged", "true", None),  # planted run DID flag drift
    ("numerics", "precision_loss_flagged", "true", None),  # ...and the sentinel bit fired
    ("numerics", "drift_host_transfers", "abs", 0),  # probe reads are sanctioned
    ("numerics", "drift_flags_clean", "abs", 0),  # healthy stream flags nothing
    ("numerics", "clean_sentinel_flags", "abs", 0),
    ("numerics", "packed_collectives_per_sync", "max", 2),  # residual rides the same buffer
    ("numerics", "sync_parity_ok", "true", None),  # world-2 two-sum fold ≤1e-6
    # serving gates (serve/, PR 9): the streaming hot loop holds the engine's
    # invariants — 0 host transfers under the STRICT guard, 0 warm retraces —
    # while 10⁴ tenant slices share one executable, the snapshot-compute is
    # provably non-blocking, and the sketches hold their error/collective
    # budgets with world-2 merge bit-parity
    ("serve", "serve_host_transfers", "abs", 0),  # windowed loop under STRICT guard
    ("serve", "serve_retraces_after_warmup", "abs", 0),  # one ring signature
    ("serve", "windowed_fallbacks", "abs", 0),  # the ring compiles (no eager demotion)
    ("serve", "windowed_parity_ok", "true", None),  # ring == recompute-from-scratch
    ("serve", "tenant_traces", "max", 1),  # 10⁴ tenants, ONE executable signature
    ("serve", "tenant_retraces_after_warmup", "abs", 0),  # tenant id is data
    ("serve", "tenant_host_transfers", "abs", 0),
    ("serve", "tenant_spot_check_ok", "true", None),  # per-slice + global exactness
    ("serve", "snapshot_nonblocking_ok", "true", None),  # updates landed mid-scrape
    ("serve", "snapshot_host_transfers", "abs", 0),
    ("serve", "hll_within_bound", "true", None),  # ±3% at 10⁵ uniques
    ("serve", "sketch_merge_parity_ok", "true", None),  # world-2 fold bit-exact
    ("serve", "sketch_collectives_budget_ok", "true", None),  # ≤1 added collective
    ("serve", "sidecar_content_type_ok", "true", None),  # text/plain; version=0.0.4
    ("serve", "sidecar_scrape_ok", "true", None),  # tm_tpu_serve_* series served
    # multi-step scan dispatch gates (engine/scan.py, PR 10): the queued drain
    # must actually amortize dispatch (>= 4x at K=8 vs the unqueued engine),
    # stay byte-identical to step-at-a-time — mid-queue quarantined batch and
    # compensated accumulation included — reuse K-bucket executables across
    # ragged tails, and hold the STRICT-guard/flush-on-observation contract
    # counter-based (machine-independent, the gate's contract): real steps
    # folded per executed dispatch — 8.0 on an aligned K=8 stream
    ("scan", "scan_dispatch_amortization_k8", "min", 4.0),
    # wall-clock evidence floor: XLA CPU exec jitter for micro executables
    # swings the measured ratio ±15% (typical ~4.2x at K=8), so the timing
    # gate is a regression tripwire, not the amortization contract
    ("scan", "scan_amortization_k8", "min", 2.0),
    ("scan", "scan_parity_ok", "true", None),  # byte-identical, riders composed
    ("scan", "scan_quarantined_batches", "eqfield", "scan_quarantine_planted"),
    ("scan", "scan_ragged_retraces_after_warmup", "abs", 0),  # K-buckets reuse warm
    ("scan", "scan_host_transfers", "abs", 0),  # drain loop under STRICT guard
    ("scan", "scan_retraces_uncaused", "abs", 0),  # every retrace attributed
    ("scan", "scan_events_per_drain_ok", "true", None),  # 1 update.scan per drain
    ("scan", "scan_flush_on_observation_ok", "true", None),  # compute() drained first
    # async pipelined dispatch gates (engine/async_dispatch.py, PR 13): the
    # double-buffered background drain must make update() a pure enqueue —
    # caller-side p50 enqueue cost <= 1/4 of the synchronous K=8 scan
    # per-step cost, gated on the PAIRED per-window ratio (machine-load noise
    # is common-mode within a window; the absolute µs figures export as
    # machine-dependent tripwires under slack) — while drains genuinely ride
    # the worker (async_dispatches truthy, overlap_us attributed both as a
    # counter and as worker-track spans in the merged timeline), parity stays
    # byte-identical with mid-queue quarantine + compensation composed, no
    # payload is ever lost to a worker failure on the clean run, and the
    # STRICT guard — propagated across the thread hop — records 0 transfers
    ("async", "async_enqueue_cost_ratio", "abs", 0.25),
    ("async", "async_enqueue_p50_us", "slack", 60.0),  # wall tripwire, not the gate
    ("async", "async_dispatches", "true", None),  # drains actually rode the worker
    ("async", "async_joins", "true", None),  # observations actually joined
    ("async", "async_overlap_ok", "true", None),  # overlap_us > 0: caller made progress
    ("async", "async_overlap_in_timeline_ok", "true", None),  # attributed in the merge
    ("async", "async_events_per_drain_ok", "true", None),  # one async.drain per drain
    ("async", "async_parity_ok", "true", None),  # byte-identical, riders composed
    ("async", "async_quarantined_batches", "eqfield", "async_quarantine_planted"),
    ("async", "async_replayed_steps", "abs", 0),  # clean run: no worker failure
    ("async", "async_retraces_after_warmup", "abs", 0),  # same cached executables
    ("async", "async_retraces_uncaused", "abs", 0),
    ("async", "async_host_transfers", "abs", 0),  # STRICT guard held across threads
    # cross-metric CSE gates (engine/statespec.py + collections.py, PR 11):
    # a 10-metric stat-scores-family collection shares ONE state-producing
    # reduction — discovered at CONSTRUCTION from declared reduction
    # signatures (no eager first-step pass, no value-comparison readback),
    # traced once, dispatched once per step, holding ~1/N unique state bytes,
    # byte-identical to independently-computed metrics with the quarantine +
    # scan riders composed on the shared state — and every in-tree role
    # resolves from the StateSpec registry (zero deprecated-convention
    # fallbacks)
    ("cse", "cse_groups", "abs", 1),  # the whole family is ONE compute group
    ("cse", "cse_discovered_at_construction", "true", None),  # no first-step pass
    ("cse", "cse_shared_reduction_traces", "abs", 1),  # the reduction traced ONCE
    ("cse", "cse_dispatches_per_step", "abs", 1.0),  # N metrics = 1 dispatch/step
    ("cse", "cse_eager_fallbacks", "abs", 0),
    ("cse", "cse_retraces_after_warmup", "abs", 0),
    ("cse", "cse_host_transfers", "abs", 0),  # STRICT guard incl. discovery
    ("cse", "cse_retraces_uncaused", "abs", 0),
    ("cse", "cse_footprint_fraction", "abs", 0.2),  # unique bytes ~1/N of nominal
    ("cse", "cse_parity_ok", "true", None),  # byte-identical, riders composed
    ("cse", "cse_quarantined_batches", "eqfield", "cse_quarantine_planted"),
    ("cse", "cse_spec_fallbacks", "abs", 0),  # every in-tree role is registry-resolved
    # SPMD sharded-state gates (parallel/sharding.py, PR 12): class-axis
    # states born distributed over a >= 2-device mesh must compute
    # bit-identically to the replicated path, hold ~1/mesh bytes per device,
    # skip the packed host gather (in-graph psum takes its place), survive
    # the full lifecycle, and run the million-class hot loop as ONE SPMD
    # executable with zero host transfers under the STRICT guard
    ("sharding", "sharding_parity_ok", "true", None),  # sharded == replicated, bit-exact
    ("sharding", "shard_states", "true", None),  # states actually placed distributed
    ("sharding", "gather_skipped", "true", None),  # packed gather skipped sharded states
    ("sharding", "psum_syncs", "true", None),  # ...and additive folds rode in-graph psum
    ("sharding", "sync_value_global_ok", "true", None),  # skipped state is already global
    ("sharding", "million_class_sharded", "true", None),  # 1M-class counters born sharded
    ("sharding", "million_class_single_graph_ok", "true", None),  # ONE update executable
    ("sharding", "sharding_retraces_after_warmup", "abs", 0),
    ("sharding", "sharding_host_transfers", "abs", 0),  # hot loop under STRICT guard
    ("sharding", "sharding_footprint_fraction", "abs", 0.30),  # per-device ~1/mesh (mesh>=4)
    ("sharding", "lifecycle_roundtrip_ok", "true", None),  # clone/pickle/state_dict/reshard
    ("sharding", "scan_compat_ok", "true", None),  # PR-10 K=8 drain, byte-identical
    # 2-D (data, state) mesh gates (parallel/sharding.py + engine/epoch.py,
    # PR 16): with a live data axis the epoch sync of replicated + sharded
    # states compiles into ONE in-graph GSPMD program — ZERO host collectives
    # and zero metadata gathers on the whole epoch path, psum counted in
    # their place, byte-parity against the world-2 host packed-sync
    # reference, all-sharded plans skipped wholesale as counted no-ops, and
    # the warm re-dispatch STRICT-guard clean with 0 retraces
    ("multichip_2d", "sync_collectives", "abs", 0),  # ZERO host collectives, live data axis
    ("multichip_2d", "sync_metadata_gathers", "abs", 0),  # metadata tiled locally, not gathered
    ("multichip_2d", "ingraph_syncs", "min", 1),  # the exchanges actually rode in-graph
    ("multichip_2d", "psum_syncs", "min", 1),  # ...with additive folds lowered to psum
    ("multichip_2d", "host_sync_collectives", "true", None),  # the HOST baseline DID gather
    ("multichip_2d", "ingraph_parity_ok", "true", None),  # byte-parity vs packed-sync reference
    ("multichip_2d", "sync_noop_plans", "min", 1),  # all-sharded plan skipped wholesale
    ("multichip_2d", "noop_value_ok", "true", None),  # ...and still computed the global value
    ("multichip_2d", "sync_collectives_total", "abs", 0),  # both legs: still zero host ops
    ("multichip_2d", "ingraph_retraces_warm", "abs", 0),  # epoch 2 reused the cached fold
    ("multichip_2d", "ingraph_host_transfers", "abs", 0),  # STRICT guard held end to end
    ("multichip_2d", "placement_2d_ok", "true", None),  # class axis over "state" only
    ("multichip_2d", "scan2d_compat_ok", "true", None),  # PR-10 K=8 drain over 2-D carries
    # federated multi-pod aggregation gates (serve/federation.py +
    # serve/quantile.py, PR 18): 4 emulated pods fold through the packed-sync
    # machinery byte-stably in canonical order — exact parity with the
    # single-pod union reference, a vanished pod yields a DEGRADED (counted)
    # fold rather than a wrong or hung value, a returning pod rejoins without
    # double-counting (watermark dedupe proven), zero host transfers outside
    # the sanctioned boundaries, and the merged KLL sketch answers p50/p99
    # inside its proven rank-error bound
    ("federation", "federation_pull_ok", "true", None),  # every pod answered round 1
    ("federation", "federation_parity_ok", "true", None),  # fold == union-stream reference
    ("federation", "federation_permutation_stable", "true", None),  # byte-stable fold
    ("federation", "federation_degraded_ok", "true", None),  # vanish -> degraded, not wrong
    ("federation", "federation_rejoin_ok", "true", None),  # rejoin without double-count
    ("federation", "federation_stale_dedupe_ok", "true", None),  # replay rejected + counted
    ("federation", "federation_degraded_folds", "min", 1),  # the degraded fold was counted
    ("federation", "federation_host_transfers", "abs", 0),  # STRICT guard end to end
    ("federation", "kll_within_bound", "true", None),  # p50/p99 <= proven rank bound
    # heavy-metric in-graph kernel gates (image/fid.py, detection/ingraph.py,
    # functional/text/bert.py, PR 15): the reference's expensive workloads run
    # engine-native — FID update+compute and the packed-route mAP hold 0
    # hot-loop host transfers under the STRICT guard with ledger-verified
    # single-graph lowering, the ragged BERTScore stream holds 0 warm
    # retraces, the sharded-FID covariance sits at ~1/mesh bytes per device,
    # and every in-graph path is parity-pinned against its host reference
    # (the retained host paths are themselves COUNTED fallbacks)
    ("heavy", "fid_parity_ok", "true", None),  # in-graph eigvalsh == host eigh
    ("heavy", "fid_host_transfers", "abs", 0),  # update stream + compute under STRICT
    ("heavy", "fid_retraces_after_warmup", "abs", 0),
    ("heavy", "fid_single_graph_ok", "true", None),  # 1 update + 1 compute executable
    ("heavy", "fid_host_eighs_clean", "abs", 0),  # knob off -> no host fallback
    ("heavy", "fid_host_eigh_counted", "true", None),  # knob on -> counted exactly once
    ("heavy", "fid_scan_parity_ok", "true", None),  # K=8 drain byte-identical
    ("heavy", "fid_sharded_parity_ok", "true", None),  # row_sharded covariance, same value
    ("heavy", "fid_sharded_footprint_fraction", "abs", 0.30),  # ~1/mesh (mesh >= 4)
    ("heavy", "map_parity_ok", "true", None),  # packed in-graph == host evaluator
    ("heavy", "map_host_transfers", "abs", 0),  # matcher + PR accumulation on device
    ("heavy", "map_retraces_after_warmup", "abs", 0),  # ragged widths share one bucket
    ("heavy", "map_single_graph_ok", "true", None),  # 1 update + 1 compute executable
    ("heavy", "map_host_fallback_counted", "true", None),  # host evaluator IS counted
    ("heavy", "bert_parity_ok", "true", None),  # bucketed == exact-shape staging
    ("heavy", "bert_warm_retraces", "abs", 0),  # ragged stream inside warm buckets
    ("heavy", "bert_host_transfers", "abs", 0),  # score path under STRICT
    ("heavy", "heavy_retraces_uncaused", "abs", 0),
    # zero-cold-start serving (PR 17): two child processes share a persist
    # dir — the warm replica must first-dispatch out of the cache, with the
    # prewarm replay proven (replays > 0), every artifact accepted (no
    # envelope rejects on a same-topology reload), and a readback-free
    # deserialize/prewarm path under the STRICT guard
    ("coldstart", "coldstart_warm_ttfd_frac", "abs", 0.10),  # warm TTFD <= 10% of uncached
    ("coldstart", "persist_hits", "true", None),  # warm leg loaded from the cache
    ("coldstart", "prewarm_replays", "true", None),  # manifest replay actually dispatched
    ("coldstart", "coldstart_envelope_rejects", "abs", 0),  # same topology -> zero rejects
    ("coldstart", "coldstart_host_transfers", "abs", 0),  # both legs under STRICT
    ("coldstart", "values_match", "true", None),  # prewarm replay is value-inert
    # fleet observability plane (PR 19): 4 emulated pods pulled + merged under
    # STRICT, the merged p99 inside the paper's GROWTH bound, pod-labeled
    # exposition byte-stable under ingest-order permutation, and the blocking
    # fleet-degraded-pulls SLO proven to flip /healthz to 503 AND recover
    ("fleet", "fleet_pull_ok", "true", None),  # every pod answered round 1
    ("fleet", "fleet_counter_parity_ok", "true", None),  # sums sum, peaks max-fold
    ("fleet", "fleet_p99_within_bound", "true", None),  # merged hist keeps the bound
    ("fleet", "fleet_permutation_stable", "true", None),  # byte-stable exposition
    ("fleet", "fleet_degraded_breach_ok", "true", None),  # 503 NAMES the breached SLO
    ("fleet", "fleet_recovery_ok", "true", None),  # fast window clears -> 200
    ("fleet", "fleet_host_transfers", "abs", 0),  # envelope cycle is host-pure
    ("fleet", "fleet_degraded_pulls", "min", 1),  # the excluded pod was counted
    ("fleet", "slo_breaches", "min", 1),  # the breach transition was counted
    ("fleet", "slo_recoveries", "min", 1),  # ...and the recovery transition
    # value provenance & freshness plane (PR 20): the STRICT-guarded K=8
    # scan + async hot loop's observation watermark equals steps-folded
    # exactly (quarantined batch counted EXCLUDED, not absorbed), the planted
    # degraded federation fold names the excluded pod on its coverage stamp,
    # the planted stale owner breaches value-freshness -> /healthz 503 naming
    # owner + staleness -> recovers, and lineage-off is byte-identical.
    ("lineage", "lineage_watermark_exact_ok", "true", None),  # watermark == steps folded
    ("lineage", "lineage_quarantined_excluded", "min", 1),  # the poison counted excluded
    ("lineage", "lineage_coverage_ok", "true", None),  # stamp NAMES the excluded pod
    ("lineage", "lineage_breach_ok", "true", None),  # 503 names owner + staleness
    ("lineage", "lineage_recovery_ok", "true", None),  # fold catches up -> 200
    ("lineage", "lineage_off_identical_ok", "true", None),  # off = byte-identical + silent
    ("lineage", "lineage_host_transfers", "abs", 0),  # provenance is host-pure
    ("lineage", "lineage_retraces_after_warmup", "max", 0),  # spans don't retrace
    ("lineage", "lineage_span_events", "min", 1),  # spans rode the event stream
    ("lineage", "lineage_coverage_folds", "min", 1),  # the attestation was counted
    ("lineage", "slo_breaches", "min", 1),  # the freshness breach transitioned
    ("lineage", "slo_recoveries", "min", 1),  # ...and recovered
)


def newest_baseline(repo: str = REPO) -> str:
    """The highest-numbered ``BENCH_r*.json`` in the repo root.

    The gate previously hardcoded one envelope file, which silently went stale
    the moment a newer round was committed; defaulting to the newest keeps the
    comparison honest without a flag on every invocation.
    """
    rounds = []
    for path in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        match = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(path))
        if match:
            rounds.append((int(match.group(1)), path))
    if not rounds:
        raise FileNotFoundError(f"no BENCH_r*.json envelope found in {repo}")
    return max(rounds)[1]

_TOL = 1e-6  # float slop for per-step ratios


def _run_smoke() -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"bench --smoke produced no JSON (rc={proc.returncode}): {proc.stderr[-500:]!r}")


def check(fresh: dict, baseline: dict) -> int:
    failures = []
    rows = []
    statuses = fresh.get("statuses", {})
    for scenario in ("engine", "epoch", "txn", "numerics", "serve", "federation", "fleet", "lineage", "scan", "async", "cse", "sharding", "multichip_2d", "heavy", "coldstart"):
        status = statuses.get(scenario, "missing")
        if status != "ok":
            failures.append(f"scenario {scenario!r} did not complete: {status}")
    def _slot(payload: dict, scenario: str) -> dict:
        # older rounds carry ``"extras": null`` or status strings in scenario
        # slots — every level must tolerate that, not KeyError on it
        extras = payload.get("extras")
        block = extras.get(scenario) if isinstance(extras, dict) else None
        return block if isinstance(block, dict) else {}

    if statuses.get("device_scenarios") == "tpu_unavailable_micro_fallback":
        # the micro fallback must carry the scenario-completeness keys: a
        # TPU-less run may downscale the device scenarios, but it can never
        # silently skip a GATED scenario block
        missing = _slot(fresh, "micro_fallback").get("scenarios_missing")
        if missing is None:
            failures.append("micro fallback lacks the scenario-completeness keys")
        elif missing:
            failures.append(f"micro fallback skipped gated scenarios: {missing}")

    for scenario, counter, kind, absolute in _CHECKS:
        got = _slot(fresh, scenario).get(counter)
        base = _slot(baseline, scenario).get(counter)
        if got is None:
            failures.append(f"{scenario}.{counter}: missing from the fresh run")
            continue
        if kind == "true":
            ok = bool(got)
            bound = "true"
        elif kind == "eqfield":  # exactness against a sibling counter of the SAME run
            expected = _slot(fresh, scenario).get(absolute)
            ok = expected is not None and float(got) == float(expected)
            bound = f"== {absolute} ({expected})"
        elif kind == "min":  # improvement floor: fresh must clear the absolute
            ok = float(got) >= float(absolute) - _TOL
            bound = f">= {absolute}"
        elif kind == "abs" or base is None:
            ok = float(got) <= float(absolute) + _TOL
            bound = f"<= {absolute}"
        elif kind == "slack":  # machine-dependent envelope: runaway detector only
            limit = max(2.0 * float(base), float(absolute))
            ok = float(got) <= limit + _TOL
            bound = f"<= {limit:g} (2x baseline {base})"
        else:  # max: no worse than the committed envelope (or the absolute floor)
            limit = max(float(base), float(absolute))
            ok = float(got) <= limit + _TOL
            bound = f"<= {limit:g} (baseline {base})"
        rows.append((f"{scenario}.{counter}", got, bound, "ok" if ok else "REGRESSED"))
        if not ok:
            failures.append(f"{scenario}.{counter}: {got} violates {bound}")

    width = max(len(r[0]) for r in rows) if rows else 0
    for name, got, bound, verdict in rows:
        print(f"  {name:<{width}}  {got!s:>10}  {bound:<28} {verdict}")
    if failures:
        print("\ncounter gate: FAILED")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("\ncounter gate: ok (hot loop holds its counter envelope + 0 host transfers)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--baseline", default=None,
                        help="committed bench envelope to gate against"
                             " (default: the newest BENCH_r*.json in the repo root)")
    parser.add_argument("--bench-json", default=None,
                        help="existing bench output to check; omitted = run bench.py --smoke fresh")
    args = parser.parse_args(argv)

    baseline_path = args.baseline or newest_baseline()
    print(f"baseline: {os.path.basename(baseline_path)}")
    with open(baseline_path) as fh:
        baseline = json.load(fh)
    try:
        if args.bench_json:
            with open(args.bench_json) as fh:
                fresh = json.load(fh)
        else:
            fresh = _run_smoke()
    except Exception as err:  # noqa: BLE001 — a broken bench is its own failure class
        print(f"counter gate: could not obtain a fresh bench run: {type(err).__name__}: {err}")
        return 2
    return check(fresh, baseline)


if __name__ == "__main__":
    sys.exit(main())
