"""Modular ExtendedEditDistance (reference ``src/torchmetrics/text/eed.py``)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.eed import _eed_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class ExtendedEditDistance(Metric):
    """EED with a per-sentence score list state (reference ``eed.py:26-123``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.text.eed import ExtendedEditDistance
        >>> metric = ExtendedEditDistance()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.2456
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    sentence_eed: List[Array]

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for param_name, param in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        """Append per-sentence scores for one batch of corpora."""
        scores = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion, None
        )
        self.sentence_eed.extend(jnp.atleast_1d(s) for s in scores)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Average EED (plus per-sentence scores when requested)."""
        # After a distributed sync the cat state is a single Array, not a list — avoid
        # truth-testing it (ambiguous for >1 element).
        state = self.sentence_eed
        is_empty = (len(state) == 0) if isinstance(state, list) else (state.size == 0)
        if is_empty:
            average = jnp.asarray(0.0)
            scores = jnp.zeros((0,))
        else:
            scores = dim_zero_cat(state if isinstance(state, list) else [state])
            average = scores.mean()
        if self.return_sentence_level_score:
            return average, scores
        return average

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
