"""Deprecated-root-import shims (reference ``text/_deprecated.py``)."""

from torchmetrics_tpu.text import (
    BLEUScore,
    CHRFScore,
    CharErrorRate,
    ExtendedEditDistance,
    MatchErrorRate,
    Perplexity,
    SQuAD,
    SacreBLEUScore,
    TranslationEditRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)
from torchmetrics_tpu.utilities.deprecation import root_alias

_BLEUScore = root_alias(BLEUScore, "text")
_CHRFScore = root_alias(CHRFScore, "text")
_CharErrorRate = root_alias(CharErrorRate, "text")
_ExtendedEditDistance = root_alias(ExtendedEditDistance, "text")
_MatchErrorRate = root_alias(MatchErrorRate, "text")
_Perplexity = root_alias(Perplexity, "text")
_SQuAD = root_alias(SQuAD, "text")
_SacreBLEUScore = root_alias(SacreBLEUScore, "text")
_TranslationEditRate = root_alias(TranslationEditRate, "text")
_WordErrorRate = root_alias(WordErrorRate, "text")
_WordInfoLost = root_alias(WordInfoLost, "text")
_WordInfoPreserved = root_alias(WordInfoPreserved, "text")
