"""Modular ROUGEScore (reference ``src/torchmetrics/text/rouge.py``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.rouge import (
    ALLOWED_ACCUMULATE_VALUES,
    ALLOWED_ROUGE_KEYS,
    _rouge_score_compute,
    _rouge_score_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class ROUGEScore(Metric):
    """ROUGE-N/L/Lsum with per-key score lists (reference ``rouge.py:27-168``).

    Example:
        >>> from torchmetrics_tpu.text import ROUGEScore
        >>> preds = 'My name is John'
        >>> target = 'Is your name John'
        >>> rouge = ROUGEScore(rouge_keys='rouge1')
        >>> result = rouge(preds, target)
        >>> print(round(float(result['rouge1_fmeasure']), 4))
        0.75
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer: Optional[Callable[[str], str]] = None,
        tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        stemmer = None
        if use_stemmer:
            try:
                from nltk.stem.porter import PorterStemmer
            except ImportError as err:
                raise ModuleNotFoundError(
                    "Stemmer support requires `nltk` which is not installed; pass `use_stemmer=False`."
                ) from err
            stemmer = PorterStemmer()
        if not isinstance(rouge_keys, tuple):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(
                    f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
                )
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]
        self.stemmer = stemmer
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate

        for rouge_key in self.rouge_keys:
            for score in ("fmeasure", "precision", "recall"):
                self.add_state(f"{rouge_key}_{score}", [], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        """Score one batch of corpora, appending per-sample values."""
        if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
            target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]

        output = _rouge_score_update(
            preds,
            target,
            self.rouge_keys_values,
            stemmer=self.stemmer,
            normalizer=self.normalizer,
            tokenizer=self.tokenizer,
            accumulate=self.accumulate,
        )
        # one device array per (key, score) per update call — per-pair device
        # scalars cost a dispatch each and made large corpora pathologically slow
        batched: Dict[str, list] = {}
        for rouge_key, metrics in output.items():
            for metric in metrics:
                for tp, value in metric.items():
                    batched.setdefault(f"rouge{rouge_key}_{tp}", []).append(float(value))
        for name, values in batched.items():
            getattr(self, name).append(jnp.asarray(values, dtype=jnp.float32))

    def compute(self) -> Dict[str, Array]:
        """Mean over accumulated per-sample scores."""
        update_output = {}
        for rouge_key in self.rouge_keys_values:
            for tp in ("fmeasure", "precision", "recall"):
                update_output[f"rouge{rouge_key}_{tp}"] = getattr(self, f"rouge{rouge_key}_{tp}")
        return _rouge_score_compute(update_output)

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
