"""Modular SacreBLEUScore (reference ``src/torchmetrics/text/sacre_bleu.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

from torchmetrics_tpu.functional.text.bleu import _bleu_score_update
from torchmetrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from torchmetrics_tpu.text.bleu import BLEUScore


class SacreBLEUScore(BLEUScore):
    """SacreBLEU — BLEU states + sacrebleu tokenizers (reference ``sacre_bleu.py:31-115``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.text.sacre_bleu import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.0
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        """Count tokenized n-grams of one batch of corpora."""
        self.numerator, self.denominator, self.preds_len, self.target_len = _bleu_score_update(
            preds, target, self.numerator, self.denominator, self.preds_len, self.target_len,
            self.n_gram, self.tokenizer,
        )
