"""Modular WER / CER / MER / WIL / WIP (reference ``src/torchmetrics/text/{wer,cer,mer,wil,wip}.py``).

All five are host-tokenized edit-distance counters feeding scalar sum states.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.cer import _cer_compute, _cer_update
from torchmetrics_tpu.functional.text.mer import _mer_compute, _mer_update
from torchmetrics_tpu.functional.text.wer import _wer_compute, _wer_update
from torchmetrics_tpu.functional.text.wil import _wil_compute, _wil_update
from torchmetrics_tpu.functional.text.wip import _wip_compute, _wip_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class WordErrorRate(Metric):
    """WER (reference ``wer.py:25-91``).

    Example:
        >>> from torchmetrics_tpu.text import WordErrorRate
        >>> preds = ['this is the prediction', 'there is an other sample']
        >>> target = ['this is the reference', 'there is another one']
        >>> wer = WordErrorRate()
        >>> print(float(wer(preds, target)))
        0.5
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate edit ops and reference word count."""
        errors, total = _wer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        """Word error rate."""
        return _wer_compute(self.errors, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class CharErrorRate(Metric):
    """CER (reference ``cer.py:25-90``)."""

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate char edit ops and reference char count."""
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        """Character error rate."""
        return _cer_compute(self.errors, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class MatchErrorRate(Metric):
    """MER (reference ``mer.py:25-90``)."""

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate edit ops and max-length count."""
        errors, total = _mer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        """Match error rate."""
        return _mer_compute(self.errors, self.total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class WordInfoLost(Metric):
    """WIL (reference ``wil.py:25-93``)."""

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate hit statistics."""
        errors, target_total, preds_total = _wil_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        """Word information lost."""
        return _wil_compute(self.errors, self.target_total, self.preds_total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)


class WordInfoPreserved(Metric):
    """WIP (reference ``wip.py:25-92``).
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Accumulate hit statistics."""
        errors, target_total, preds_total = _wip_update(preds, target)
        self.errors = self.errors + errors
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        """Word information preserved."""
        return _wip_compute(self.errors, self.target_total, self.preds_total)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
