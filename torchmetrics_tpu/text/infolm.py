"""Modular InfoLM (reference ``src/torchmetrics/text/infolm.py``).

State design mirrors BERTScore: with ``model_name_or_path`` the metric tokenizes at
``update`` and stores fixed-width ``input_ids``/``attention_mask`` ARRAYS as cat
states that ride the cross-process gather — a multi-host eval computes sentence
distributions (and corpus-wide idf) over the full gathered corpus. With an injected
``model`` callable (sentences -> distributions) the raw-sentence buffers are kept,
which aggregate per-host only.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.infolm import (
    _InformationMeasure,
    infolm,
    make_hf_masked_lm_distribution_fns,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class InfoLM(Metric):
    """InfoLM with injected masked-LM (reference ``infolm.py:33-222``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False

    preds: List[str]
    target: List[str]
    pred_input_ids: List[Array]
    pred_attention_mask: List[Array]
    target_input_ids: List[Array]
    target_attention_mask: List[Array]

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        model: Optional[Callable] = None,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.model = model
        self.return_sentence_level_score = return_sentence_level_score
        # resolved lazily; dropped on pickle (closures over live HF models)
        self._tokenize_fn: Optional[Callable] = None
        self._dist_fn: Optional[Callable] = None
        self._resolved = False

        # tokenized-tensor states: fixed-width int arrays ride the array gather
        self.add_state("pred_input_ids", [], dist_reduce_fx="cat")
        self.add_state("pred_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")
        # raw-sentence fallback for injected-model configurations (host data)
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def _resolve(self) -> None:
        if self._resolved:
            return
        if self.model is None and self.model_name_or_path is not None:
            self._tokenize_fn, self._dist_fn, _ = make_hf_masked_lm_distribution_fns(
                self.model_name_or_path, temperature=self.temperature, idf=self.idf
            )
        self._resolved = True

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Tokenize and buffer (arrays on the HF path, raw sentences otherwise)."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError("Number of predicted and reference sentences must be the same!")
        self._resolve()
        if self._tokenize_fn is not None:
            p_ids, p_attn = self._tokenize_fn(list(preds))
            t_ids, t_attn = self._tokenize_fn(list(target))
            self.pred_input_ids.append(jnp.asarray(p_ids))
            self.pred_attention_mask.append(jnp.asarray(p_attn))
            self.target_input_ids.append(jnp.asarray(t_ids))
            self.target_attention_mask.append(jnp.asarray(t_attn))
        else:
            self.preds.extend(preds)
            self.target.extend(target)

    def _has_tokenized_state(self) -> bool:
        state = self.pred_input_ids
        return len(state) > 0 if isinstance(state, list) else state.size > 0

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Score the gathered corpus (tokenized path) or buffered sentences."""
        if self._has_tokenized_state():
            self._resolve()
            measure = _InformationMeasure(self.information_measure, self.alpha, self.beta)
            preds_distribution = self._dist_fn(
                dim_zero_cat(self.pred_input_ids), dim_zero_cat(self.pred_attention_mask)
            )
            target_distribution = self._dist_fn(
                dim_zero_cat(self.target_input_ids), dim_zero_cat(self.target_attention_mask)
            )
            scores = measure(preds_distribution, target_distribution)
            if self.return_sentence_level_score:
                return scores.mean(), scores
            return scores.mean()
        return infolm(
            self.preds,
            self.target,
            model_name_or_path=self.model_name_or_path,
            temperature=self.temperature,
            information_measure=self.information_measure,
            idf=self.idf,
            alpha=self.alpha,
            beta=self.beta,
            model=self.model,
            return_sentence_level_score=self.return_sentence_level_score,
        )

    def __getstate__(self) -> dict:
        """Resolved HF closures are unpicklable — drop and re-resolve lazily."""
        state = dict(super().__getstate__())
        state.update(_resolved=False, _tokenize_fn=None, _dist_fn=None)
        return state

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
