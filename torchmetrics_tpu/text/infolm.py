"""Modular InfoLM (reference ``src/torchmetrics/text/infolm.py``)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.infolm import infolm
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class InfoLM(Metric):
    """InfoLM with injected masked-LM (reference ``infolm.py:33-222``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False

    preds: List[str]
    target: List[str]

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        model: Optional[Callable] = None,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.temperature = temperature
        self.information_measure = information_measure
        self.idf = idf
        self.alpha = alpha
        self.beta = beta
        self.model = model
        self.return_sentence_level_score = return_sentence_level_score
        # String buffers: raw (None) states — arrays-only sync cannot cat host strings.
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Buffer raw sentences."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        self.preds.extend(preds)
        self.target.extend(target)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Score all buffered sentences with the injected model."""
        return infolm(
            self.preds,
            self.target,
            model_name_or_path=self.model_name_or_path,
            temperature=self.temperature,
            information_measure=self.information_measure,
            idf=self.idf,
            alpha=self.alpha,
            beta=self.beta,
            model=self.model,
            return_sentence_level_score=self.return_sentence_level_score,
        )

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
