"""Modular BERTScore (reference ``src/torchmetrics/text/bert.py``).

Raw sentence list states (cat) — tokenization/model forward deferred to compute, like
the reference which stores tokenized tensors and runs the model at compute
(``bert.py:192-195``). ``model_name_or_path`` loads a HF transformer (Flax-first,
offline-clean errors); alternatively inject ``model``/``user_tokenizer`` callables.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax

from torchmetrics_tpu.functional.text.bert import bert_score
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class BERTScore(Metric):
    """BERTScore with injected embedder (reference ``bert.py:56-232``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds: List[str]
    target: List[str]

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Optional[Callable] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 512,
        batch_size: int = 64,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.model = model
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.idf = idf
        self.max_length = max_length
        self.batch_size = batch_size
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        # Strings are host data, not arrays — raw (None) states pass through sync
        # untouched; the array-only gather path cannot concatenate them. Cross-host
        # aggregation therefore happens per-host (the reference avoids this by storing
        # tokenized tensors instead; with an injected tokenizer users can do the same).
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Buffer raw sentences."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError("Number of predicted and reference sentences must be the same!")
        self.preds.extend(preds)
        self.target.extend(target)

    def compute(self) -> Dict[str, Array]:
        """Run the injected model over all buffered sentences and match greedily."""
        return bert_score(
            preds=self.preds,
            target=self.target,
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
            user_forward_fn=self.user_forward_fn,
            idf=self.idf,
            max_length=self.max_length,
            batch_size=self.batch_size,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
        )

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
