"""Modular BERTScore (reference ``src/torchmetrics/text/bert.py``).

State design follows the reference (``bert.py:192-195``): when a tokenizer is
available (``model_name_or_path`` or ``user_tokenizer``), ``update`` tokenizes
immediately and stores padded ``input_ids``/``attention_mask`` ARRAYS — fixed-width,
so they ride the cross-process array gather and a multi-host eval computes over the
full corpus (including corpus-wide idf). Only with no tokenizer at all does the
metric fall back to raw sentence-list states, which are host data and aggregate
per-host only.

Scoring rides the bucketed staging of ``functional/text/bert.py``: the
epoch-end corpus (whatever its pair count) pads up to the engine's
power-of-two buckets before the model forward and the jitted greedy-cosine
core, and IDF weighting is a device-side table gather — ragged eval corpora
stop retracing and stop touching host in the score path
(``TORCHMETRICS_TPU_BERT_BUCKETS`` opts out).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.bert import (
    _resolve_model_and_tokenizer,
    _score_from_tokens,
    bert_score,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class BERTScore(Metric):
    """BERTScore with injected embedder (reference ``bert.py:56-232``)."""

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    preds: List[str]
    target: List[str]
    pred_input_ids: List[Array]
    pred_attention_mask: List[Array]
    target_input_ids: List[Array]
    target_attention_mask: List[Array]

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Callable] = None,
        user_tokenizer: Optional[Callable] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 512,
        batch_size: int = 64,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.model = model
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.idf = idf
        self.max_length = max_length
        self.batch_size = batch_size
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        # resolved lazily on first use (loading the HF model at construction would
        # make the ctor heavy and pickling awkward)
        self._forward_fn: Optional[Callable] = None
        self._tokenize_fn: Optional[Callable] = None
        self._pad_width = max_length
        self._resolved = False

        # tokenized-tensor states (reference parity): fixed-width int arrays that
        # the cross-process gather concatenates like any other cat state
        self.add_state("pred_input_ids", [], dist_reduce_fx="cat")
        self.add_state("pred_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")
        # raw-sentence fallback for the no-tokenizer configuration: host data, raw
        # (None) states pass through sync untouched — per-host aggregation only
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def _resolve(self) -> None:
        # loads the model too, not just the tokenizer: the tokenizer's pad width must
        # be capped by the model's position-embedding capacity (model_max_length), so
        # a tokenizer-only resolution could store arrays the forward cannot consume
        if self._resolved:
            return
        forward, tokenizer, pad_width = _resolve_model_and_tokenizer(
            self.model_name_or_path, self.num_layers, self.model, self.user_tokenizer, self.max_length
        )
        self._forward_fn = self.user_forward_fn if self.user_forward_fn is not None else forward
        self._tokenize_fn = tokenizer
        self._pad_width = pad_width
        self._resolved = True

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        """Tokenize and buffer (arrays when a tokenizer exists, else raw sentences)."""
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [target]
        if len(preds) != len(target):
            raise ValueError("Number of predicted and reference sentences must be the same!")
        self._resolve()
        if self._tokenize_fn is not None:
            p_tok = self._tokenize_fn(list(preds))
            t_tok = self._tokenize_fn(list(target))
            self.pred_input_ids.append(self._to_width(p_tok["input_ids"]))
            self.pred_attention_mask.append(self._to_width(p_tok["attention_mask"]))
            self.target_input_ids.append(self._to_width(t_tok["input_ids"]))
            self.target_attention_mask.append(self._to_width(t_tok["attention_mask"]))
        else:
            self.preds.extend(preds)
            self.target.extend(target)

    def _to_width(self, arr: Any) -> Array:
        """Right-pad a tokenized batch to the fixed state width.

        User tokenizers commonly pad dynamically (``padding='longest'``), giving a
        different width per batch — but cat states (and the cross-process gather's
        pre-concatenate) need one width. Zero padding is score-neutral: every
        similarity/idf term is attention-mask-weighted.
        """
        arr = jnp.asarray(arr)
        width = self._pad_width
        if arr.shape[1] > width:
            capped = width < self.max_length
            constraint = (
                f"the model's position-embedding capacity ({width}, which capped your"
                f" max_length={self.max_length})" if capped else f"max_length={width}"
            )
            remedy = (
                "truncate in the tokenizer or use a model with more positions"
                if capped else "truncate in the tokenizer or raise `max_length`"
            )
            raise ValueError(
                f"Tokenizer produced width {arr.shape[1]} > {constraint}; {remedy}"
                " (silent truncation here would corrupt scores)."
            )
        if arr.shape[1] < width:
            arr = jnp.pad(arr, ((0, 0), (0, width - arr.shape[1])))
        return arr

    def _has_tokenized_state(self) -> bool:
        state = self.pred_input_ids
        return len(state) > 0 if isinstance(state, list) else state.size > 0

    def compute(self) -> Dict[str, Array]:
        """Score the gathered corpus (tokenized-array path) or buffered sentences."""
        if self._has_tokenized_state():
            if self.rescale_with_baseline:
                raise ValueError(
                    "Baseline rescaling requires downloadable baseline files, which are unavailable."
                )
            self._resolve()
            if self._forward_fn is None:
                from torchmetrics_tpu.functional.text.bert import _validate_model_inputs

                _validate_model_inputs(None, self._tokenize_fn)  # curated error
            pred_tok = {
                "input_ids": dim_zero_cat(self.pred_input_ids),
                "attention_mask": dim_zero_cat(self.pred_attention_mask),
            }
            tgt_tok = {
                "input_ids": dim_zero_cat(self.target_input_ids),
                "attention_mask": dim_zero_cat(self.target_attention_mask),
            }
            precision, recall, f1 = _score_from_tokens(pred_tok, tgt_tok, self._forward_fn, self.idf)
            return {"precision": precision, "recall": recall, "f1": f1}
        return bert_score(
            preds=self.preds,
            target=self.target,
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            model=self.model,
            user_tokenizer=self.user_tokenizer,
            user_forward_fn=self.user_forward_fn,
            idf=self.idf,
            max_length=self.max_length,
            batch_size=self.batch_size,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
        )

    def __getstate__(self) -> Dict[str, Any]:
        """Resolved HF callables close over live model objects — drop them and
        re-resolve lazily after unpickling."""
        state = dict(super().__getstate__())
        state.update(_resolved=False, _forward_fn=None, _tokenize_fn=None)
        return state

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
