"""Modular TranslationEditRate (reference ``src/torchmetrics/text/ter.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class TranslationEditRate(Metric):
    """TER (reference ``ter.py:27-127``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.text.ter import TranslationEditRate
        >>> metric = TranslationEditRate()
        >>> _ = metric.update(preds, target)
        >>> print(round(float(metric.compute()), 4))
        0.2222
    """

    is_differentiable: bool = False
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(normalize, bool):
            raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
        if not isinstance(no_punctuation, bool):
            raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
        if not isinstance(lowercase, bool):
            raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
        if not isinstance(asian_support, bool):
            raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", jnp.asarray(0.0), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def update(
        self,
        preds: Union[str, Sequence[str]],
        target: Union[Sequence[str], Sequence[Sequence[str]]],
    ) -> None:
        """Accumulate edit counts of one batch of corpora."""
        self.total_num_edits, self.total_tgt_length, sentence_scores = _ter_update(
            preds,
            target,
            self.tokenizer,
            self.total_num_edits,
            self.total_tgt_length,
            [] if self.return_sentence_level_score else None,
        )
        if self.return_sentence_level_score and sentence_scores:
            self.sentence_ter.extend(jnp.atleast_1d(s) for s in sentence_scores)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Corpus TER (plus per-sentence scores when requested)."""
        ter = _ter_compute(self.total_num_edits, self.total_tgt_length)
        if self.return_sentence_level_score:
            return ter, dim_zero_cat(self.sentence_ter)
        return ter

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
