"""Modular CHRFScore (reference ``src/torchmetrics/text/chrf.py``).

Six fixed-shape per-order arrays instead of the reference's dozens of dynamically
named scalar states (``text/chrf.py:96-110``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class CHRFScore(Metric):
    """chrF / chrF++ (reference ``chrf.py:30-178``).

    Example:
        >>> from torchmetrics_tpu.text import CHRFScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat']]
        >>> chrf = CHRFScore()
        >>> print(round(float(chrf(preds, target)), 4))
        0.4942
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        self.n_char_order = n_char_order
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        self.n_word_order = n_word_order
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)

        self.add_state("total_preds_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_preds_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("total_target_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_target_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("total_matching_char_n_grams", jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("total_matching_word_n_grams", jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if self.return_sentence_level_score:
            self.add_state("sentence_chrf_score", [], dist_reduce_fx="cat")

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        """Accumulate n-gram statistics of one batch of corpora."""
        (
            self.total_preds_char_n_grams,
            self.total_preds_word_n_grams,
            self.total_target_char_n_grams,
            self.total_target_word_n_grams,
            self.total_matching_char_n_grams,
            self.total_matching_word_n_grams,
            sentence_scores,
        ) = _chrf_score_update(
            preds,
            target,
            self.total_preds_char_n_grams,
            self.total_preds_word_n_grams,
            self.total_target_char_n_grams,
            self.total_target_word_n_grams,
            self.total_matching_char_n_grams,
            self.total_matching_word_n_grams,
            self.n_char_order,
            self.n_word_order,
            self.n_order,
            self.beta,
            self.lowercase,
            self.whitespace,
            [] if self.return_sentence_level_score else None,
        )
        if self.return_sentence_level_score and sentence_scores:
            self.sentence_chrf_score.extend(sentence_scores)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        """Corpus chrF (plus per-sentence scores when requested)."""
        score = _chrf_score_compute(
            self.total_preds_char_n_grams,
            self.total_preds_word_n_grams,
            self.total_target_char_n_grams,
            self.total_target_word_n_grams,
            self.total_matching_char_n_grams,
            self.total_matching_word_n_grams,
            self.n_order,
            self.beta,
        )
        if self.return_sentence_level_score:
            return score, dim_zero_cat([jnp.atleast_1d(s) for s in self.sentence_chrf_score])
        return score

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
