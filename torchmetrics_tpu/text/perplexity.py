"""Modular Perplexity (reference ``src/torchmetrics/text/perplexity.py``)."""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class Perplexity(Metric):
    """Perplexity with Σ−logp / count states (reference ``perplexity.py:28-111``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.text import Perplexity
        >>> logits = jnp.log(jnp.asarray([[[0.7, 0.1, 0.2], [0.25, 0.5, 0.25]],
        ...                               [[0.1, 0.1, 0.8], [0.3, 0.4, 0.3]]]))
        >>> target = jnp.asarray([[0, 1], [2, 1]])
        >>> perp = Perplexity()
        >>> print(round(float(perp(logits, target)), 2))
        1.73
    """

    is_differentiable: bool = True
    higher_is_better: bool = False
    full_state_update: bool = False
    plot_lower_bound: float = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("count", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate negative log likelihood and token count."""
        total_log_probs, count = _perplexity_update(preds, target, self.ignore_index)
        self.total_log_probs = self.total_log_probs + total_log_probs
        self.count = self.count + count

    def compute(self) -> Array:
        """Perplexity over all tokens."""
        return _perplexity_compute(self.total_log_probs, self.count)

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
