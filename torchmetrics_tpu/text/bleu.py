"""Modular BLEUScore (reference ``src/torchmetrics/text/bleu.py``)."""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class BLEUScore(Metric):
    """BLEU with per-order numerator/denominator tensor states (reference ``bleu.py:28-124``).

    Example:
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu = BLEUScore()
        >>> print(round(float(bleu(preds, target)), 4))
        0.7598
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram

        self.add_state("preds_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(self.n_gram), dist_reduce_fx="sum")

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        """Count n-grams of one batch of corpora."""
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
        self.numerator, self.denominator, self.preds_len, self.target_len = _bleu_score_update(
            preds_, target_, self.numerator, self.denominator, self.preds_len, self.target_len,
            self.n_gram, _tokenize_fn,
        )

    def compute(self) -> Array:
        """Corpus BLEU."""
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.weights, self.smooth
        )

    def plot(self, val: Optional[Array] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
