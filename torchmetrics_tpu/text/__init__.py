"""Modular text metrics (reference ``src/torchmetrics/text/__init__.py``)."""

from torchmetrics_tpu.text.bert import BERTScore
from torchmetrics_tpu.text.bleu import BLEUScore
from torchmetrics_tpu.text.chrf import CHRFScore
from torchmetrics_tpu.text.eed import ExtendedEditDistance
from torchmetrics_tpu.text.infolm import InfoLM
from torchmetrics_tpu.text.perplexity import Perplexity
from torchmetrics_tpu.text.rouge import ROUGEScore
from torchmetrics_tpu.text.sacre_bleu import SacreBLEUScore
from torchmetrics_tpu.text.squad import SQuAD
from torchmetrics_tpu.text.ter import TranslationEditRate
from torchmetrics_tpu.text.wer import (
    CharErrorRate,
    MatchErrorRate,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

__all__ = [
    "BERTScore",
    "BLEUScore",
    "CHRFScore",
    "CharErrorRate",
    "ExtendedEditDistance",
    "InfoLM",
    "MatchErrorRate",
    "Perplexity",
    "ROUGEScore",
    "SQuAD",
    "SacreBLEUScore",
    "TranslationEditRate",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
