"""Modular SQuAD (reference ``src/torchmetrics/text/squad.py``)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)
from torchmetrics_tpu.metric import Metric

Array = jax.Array


class SQuAD(Metric):
    """SQuAD EM/F1 with sum states (reference ``squad.py:26-117``).

    Example:
        >>> from torchmetrics_tpu.text import SQuAD
        >>> preds = [{'prediction_text': '1976', 'id': '56e10a3be3433e1400422b22'}]
        >>> target = [{'answers': {'answer_start': [97], 'text': ['1976']}, 'id': '56e10a3be3433e1400422b22'}]
        >>> squad = SQuAD()
        >>> result = squad(preds, target)
        >>> print(float(result['exact_match']), float(result['f1']))
        100.0 100.0
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = False
    plot_lower_bound: float = 0.0
    plot_upper_bound: float = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: PREDS_TYPE, target: TARGETS_TYPE) -> None:
        """Accumulate EM/F1 sums for one batch of QA pairs."""
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        self.f1_score = self.f1_score + f1
        self.exact_match = self.exact_match + exact_match
        self.total = self.total + total

    def compute(self) -> Dict[str, Array]:
        """Mean EM/F1 in percent."""
        return _squad_compute(self.f1_score, self.exact_match, self.total)

    def plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        return self._plot(val, ax)
