"""TPU-native compute kernels (Pallas) for the framework's hot ops.

The reference library leans on ATen scatter/bincount kernels for its heavy counting ops
(``src/torchmetrics/utilities/data.py:211-241``, the per-threshold scatter in
``functional/classification/precision_recall_curve.py:205-243``). On TPU those lower to
serialized scatter-adds; the kernels here re-express them as fused compare + MXU matmul
passes that never materialise the comparison tensor in HBM.
"""

from torchmetrics_tpu.ops.multi_threshold import multi_threshold_counts

__all__ = ["multi_threshold_counts"]
