"""Fused multi-threshold counting kernel — the binned-curve hot op.

Every binned curve metric (PR-curve, ROC, AUROC, AP, the fixed-operating-point family;
reference ``functional/classification/precision_recall_curve.py:205-243``) reduces to the
same counting problem: for each threshold ``t`` and class ``c``,

    tp[t, c]      = #{n : preds[n, c] >= t and positive[n, c] and valid[n, c]}
    predpos[t, c] = #{n : preds[n, c] >= t and valid[n, c]}

The torch reference materialises the ``(N, C, T)`` comparison tensor and scatter-adds it.
On TPU both halves are wrong: the comparison tensor burns HBM bandwidth and scatters
serialise. Four strategies live here; the dispatch is driven by an on-device crossover
sweep (TPU v5e, T=200, target-int carry probe, r04):

  | N      | C    | pallas | einsum | histogram | flat-matmul |
  |--------|------|--------|--------|-----------|-------------|
  | 8192   | 10   | 100 µs | noisy* | 7 100 µs  | noisy*      |
  | 8192   | 20   |        | 37 µs  |           | 171 µs      |
  | 8192   | 100  | 849 µs | 16 µs  | 66 784 µs | 493 µs      |
  | 65536  | 10   | 797 µs | 114 µs |           | 370 µs      |
  | 65536  | 100  |        | 1009µs |           |             |

  *at 8192x10 the whole op reads 328 KB — dispatch-bound, every fused impl sits
  inside measurement noise (0.9–277 µs across repeats); only pallas (~100 µs) and
  histogram are consistent losers.

* **compare-reduce einsum** (the TPU default): ``nct,nc->tc``. XLA fuses the
  comparison generation into the reduction — the C=100 cell runs in 16 µs where a
  materialised bf16 tensor alone would cost >400 µs of HBM writes — so this is
  O(N*C*T) compare+mac work with only the O(N*C) input read. f32 accumulation is
  exact below 2**24.
* **bucketised histogram** (non-TPU, or shapes past ``_EINSUM_MAX_BYTES`` where a
  failed fusion would materialise): searchsorted + one ``N*C``-element scatter per
  histogram + suffix sums; scatter and binary-search gathers are fine on CPU.
* **flat-matmul** (``impl="flat_matmul"``): lazily generated class-one-hot and
  comparison operands contracted on the MXU — O(N*C^2*T) FLOPs, one HBM pass. Wins
  some small-C cells but loses robustly by C=20; kept selectable, not auto-picked.
* **Pallas kernel** (``impl="pallas"``): the explicit-pipeline Mosaic formulation of
  flat-matmul. Beaten by XLA's own fusion everywhere measured (its block pipeline
  re-materialises the stripes XLA never writes); kept as the interpret-mode test
  oracle, exactly like ``stat_counts.py``'s pallas path.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

try:  # pallas needs a recent jaxlib; fall back silently if absent
    from jax.experimental import pallas as pl

    _PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    _PALLAS_AVAILABLE = False

# VMEM budget for one block's working set (bytes). Half of the ~16 MB/core so the
# pipeline can double-buffer.
_VMEM_BUDGET = 6 * 2**20
_MAX_BLOCK_ROWS = 1 << 20
# f32 accumulation is exact for integer counts below 2**24.
_EXACT_F32_LIMIT = 1 << 24
# Guard on the einsum path's comparison tensor (bf16 bytes): XLA fuses it away in
# every configuration measured, but a future fusion failure at these sizes would
# materialise it — past this, take the memory-light histogram instead.
_EINSUM_MAX_BYTES = 1 << 31


def _kernel(p_ref, y_ref, v_ref, cls_ref, thr_ref, tp_ref, pp_ref):
    """One flattened sample block: fused compare + two MXU matmuls.

    p (1, B) f32 scores; y (1, B) bf16 positive*valid; v (1, B) bf16 valid;
    cls (1, B) i32 class id per row; thr (1, T) f32;
    tp/pp (C, T) f32 accumulators.
    """
    i = pl.program_id(0)
    num_classes = tp_ref.shape[0]
    block = p_ref.shape[1]
    cmp = (p_ref[0][:, None] >= thr_ref[0][None, :]).astype(jnp.bfloat16)  # (B, T)
    eq = (
        jax.lax.broadcasted_iota(jnp.int32, (num_classes, block), 0) == cls_ref[0][None, :]
    ).astype(jnp.bfloat16)  # (C, B)
    w_tp = eq * y_ref[0][None, :]
    w_pp = eq * v_ref[0][None, :]
    dims = (((1,), (0,)), ((), ()))
    tp_part = jax.lax.dot_general(w_tp, cmp, dims, preferred_element_type=jnp.float32)
    pp_part = jax.lax.dot_general(w_pp, cmp, dims, preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        tp_ref[:] = jnp.zeros_like(tp_ref)
        pp_ref[:] = jnp.zeros_like(pp_ref)

    tp_ref[:] += tp_part
    pp_ref[:] += pp_part


def _block_rows(num_classes: int, num_thresholds: int) -> int:
    """Samples per block so the VMEM working set fits, lane-aligned.

    Returns 0 when no admissible block exists (fallback path).
    """
    # per flattened row: p f32 + cls i32 + y/v bf16 + cmp row (T bf16) + three
    # (C,) bf16 weight-stripe columns
    bytes_per_row = 12 + 2 * num_thresholds + 6 * num_classes
    out_bytes = 2 * num_classes * num_thresholds * 4
    budget = _VMEM_BUDGET - out_bytes
    if budget <= 0:
        return 0
    max_rows = min(budget // bytes_per_row, _MAX_BLOCK_ROWS)
    # flat block length (rows * C) must be a multiple of 128 lanes
    unit = 128 // math.gcd(num_classes, 128)
    max_block = (max_rows // num_classes // unit) * unit
    if max_block < unit:
        return 0
    return min(max_block, 4096)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _counts_pallas(
    preds: Array, positive: Array, valid: Array, thresholds: Array, interpret: bool = False
) -> Tuple[Array, Array]:
    n, c = preds.shape
    t = thresholds.shape[0]
    if n == 0:  # zero grid steps would leave the output buffers uninitialised
        zeros = jnp.zeros((t, c), jnp.int32)
        return zeros, zeros
    blk = _block_rows(c, t)
    pad = (-n) % blk
    if pad:
        preds = jnp.pad(preds, ((0, pad), (0, 0)))
        positive = jnp.pad(positive, ((0, pad), (0, 0)))
        valid = jnp.pad(valid, ((0, pad), (0, 0)))
    nrows = preds.shape[0] * c
    p_flat = preds.astype(jnp.float32).reshape(1, nrows)
    v_flat = valid.reshape(1, nrows).astype(jnp.bfloat16)
    y_flat = positive.reshape(1, nrows).astype(jnp.bfloat16) * v_flat
    cls = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32)[None, :], (preds.shape[0], c)).reshape(1, nrows)
    block = blk * c
    spec = pl.BlockSpec((1, block), lambda i: (0, i))
    out_spec = pl.BlockSpec((c, t), lambda i: (0, 0))
    tp, pp = pl.pallas_call(
        _kernel,
        grid=(nrows // block,),
        in_specs=[spec, spec, spec, spec, pl.BlockSpec((1, t), lambda i: (0, 0))],
        out_specs=(out_spec, out_spec),
        out_shape=(
            jax.ShapeDtypeStruct((c, t), jnp.float32),
            jax.ShapeDtypeStruct((c, t), jnp.float32),
        ),
        interpret=interpret,
    )(p_flat, y_flat, v_flat, cls, thresholds.astype(jnp.float32).reshape(1, t))
    return tp.T.astype(jnp.int32), pp.T.astype(jnp.int32)


def _counts_einsum(
    preds: Array, positive: Array, valid: Array, thresholds: Array
) -> Tuple[Array, Array]:
    """Materialised comparison tensor contracted on the MXU — O(N*C*T) bytes/FLOPs."""
    cmp = (preds[:, :, None] >= thresholds[None, None, :]).astype(jnp.bfloat16)  # (N, C, T)
    v_f = valid.astype(jnp.bfloat16)
    y_f = positive.astype(jnp.bfloat16) * v_f
    tp = jnp.einsum("nct,nc->tc", cmp, y_f, preferred_element_type=jnp.float32)
    pp = jnp.einsum("nct,nc->tc", cmp, v_f, preferred_element_type=jnp.float32)
    return tp.astype(jnp.int32), pp.astype(jnp.int32)


def _counts_flat_matmul(
    preds: Array, positive: Array, valid: Array, thresholds: Array
) -> Tuple[Array, Array]:
    """Lazily generated class-one-hot x comparison operands contracted on the MXU.

    The pallas kernel's algorithm in plain XLA (cf. ``stat_counts.py``'s
    onehot-matmul): both bf16 operands are elementwise generators XLA fuses into the
    matmul, so HBM traffic is the single input read; FLOPs are O(N*C^2*T).
    """
    n, c = preds.shape
    f = n * c
    p = preds.astype(jnp.float32).reshape(f)
    v = valid.reshape(f).astype(jnp.bfloat16)
    y = positive.reshape(f).astype(jnp.bfloat16) * v
    ci = jnp.arange(c, dtype=jnp.int32)
    cls = jnp.broadcast_to(ci[None, :], (n, c)).reshape(f)
    cls_oh = (cls[:, None] == ci[None, :]).astype(jnp.bfloat16)  # (F, C), fused
    cmp = (p[:, None] >= thresholds.astype(jnp.float32)[None, :]).astype(jnp.bfloat16)  # (F, T), fused
    dims = (((0,), (0,)), ((), ()))
    tp = jax.lax.dot_general(cls_oh * y[:, None], cmp, dims, preferred_element_type=jnp.float32)
    pp = jax.lax.dot_general(cls_oh * v[:, None], cmp, dims, preferred_element_type=jnp.float32)
    return tp.T.astype(jnp.int32), pp.T.astype(jnp.int32)


def _counts_histogram(
    preds: Array, positive: Array, valid: Array, thresholds: Array
) -> Tuple[Array, Array]:
    """Bucketise + histogram + suffix-sum: memory-light, scatter over N*C elements."""
    n_thresh = thresholds.shape[0]
    num_classes = preds.shape[1]
    order = jnp.argsort(thresholds)
    sorted_thr = thresholds[order]
    # bin[n, c] = #{t : sorted_thr[t] <= preds[n, c]}; NaN pinned to bin 0 to match
    # ``preds >= t`` being False for NaN.
    bins = jnp.searchsorted(sorted_thr, preds, side="right")
    bins = jnp.where(jnp.isnan(preds), 0, bins)
    flat_idx = bins + (n_thresh + 1) * jnp.arange(num_classes, dtype=bins.dtype)[None, :]
    flat_idx = jnp.where(valid, flat_idx, -1)
    valid_i = valid.astype(jnp.int32)
    pos_w = positive.astype(jnp.int32) * valid_i
    zeros = jnp.zeros(num_classes * (n_thresh + 1), dtype=jnp.int32)
    pos_hist = zeros.at[flat_idx.ravel()].add(pos_w.ravel(), mode="drop").reshape(num_classes, n_thresh + 1)
    tot_hist = zeros.at[flat_idx.ravel()].add(valid_i.ravel(), mode="drop").reshape(num_classes, n_thresh + 1)
    pos_cum = jnp.cumsum(pos_hist, axis=1)
    tot_cum = jnp.cumsum(tot_hist, axis=1)
    # preds >= sorted_thr[t] <=> bin > t: suffix sums past t, unsorted back at the end
    tp_sorted = (pos_cum[:, -1:] - pos_cum[:, :n_thresh]).T
    predpos_sorted = (tot_cum[:, -1:] - tot_cum[:, :n_thresh]).T
    inv_order = jnp.argsort(order)
    return tp_sorted[inv_order], predpos_sorted[inv_order]


def multi_threshold_counts(
    preds: Array, positive: Array, valid: Array, thresholds: Array, impl: str = "auto"
) -> Tuple[Array, Array]:
    """``tp[t, c]`` and ``predpos[t, c]`` for every threshold, exact integer counts.

    Args:
        preds: ``(N, C)`` scores (NaN counts as below every threshold).
        positive: ``(N, C)`` 0/1 ground-truth membership.
        valid: ``(N, C)`` bool mask of samples to count.
        thresholds: ``(T,)`` thresholds, any order.
        impl: ``"auto"`` (crossover-table dispatch — module docstring), or an
            explicit ``"einsum"`` / ``"histogram"`` / ``"flat_matmul"`` / ``"pallas"``.

    Returns:
        ``(tp, predpos)``, both ``(T, C)`` int32.
    """

    from torchmetrics_tpu.ops._dispatch import inputs_on_tpu

    n, c = preds.shape
    t = thresholds.shape[0]
    if impl == "auto":
        # crossover sweep (docstring table): einsum's fused compare-reduce wins or
        # ties every TPU cell; histogram wins off-TPU and guards the fusion cap
        if (
            inputs_on_tpu(preds)
            and n < _EXACT_F32_LIMIT
            and 2 * n * c * t <= _EINSUM_MAX_BYTES
        ):
            impl = "einsum"
        else:
            impl = "histogram"
    if impl in ("einsum", "flat_matmul", "pallas") and n >= _EXACT_F32_LIMIT:
        # these impls accumulate counts in f32; past 2**24 they would silently
        # saturate — only the integer histogram stays exact
        raise ValueError(
            f"impl={impl!r} accumulates in f32 and is only exact below {_EXACT_F32_LIMIT} samples"
            f" (got {n}); use impl='histogram' (or 'auto')"
        )
    if impl == "einsum":
        return _counts_einsum(preds, positive, valid, thresholds)
    if impl == "histogram":
        return _counts_histogram(preds, positive, valid, thresholds)
    if impl == "flat_matmul":
        return _counts_flat_matmul(preds, positive, valid, thresholds)
    if impl == "pallas":
        if not _PALLAS_AVAILABLE or _block_rows(c, t) == 0:
            raise ValueError("pallas impl unavailable for this shape/jaxlib")
        # off-TPU the Mosaic kernel cannot compile — run the documented
        # interpret-mode oracle instead of dying in lowering
        return _counts_pallas(preds, positive, valid, thresholds, interpret=not inputs_on_tpu(preds))
    raise ValueError(f"unknown impl {impl!r}")
