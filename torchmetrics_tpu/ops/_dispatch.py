"""Shared dispatch helpers for the fused-op gates."""

from __future__ import annotations

import jax


def inputs_on_tpu(x) -> bool:
    """Whether ``x`` lives on (or will be placed on) a TPU.

    Dispatch on the concrete committed device when available — explicit placement
    on a non-default backend must pick the matching path — falling back to the
    default backend for tracers, whose device is unknown at trace time.
    """
    try:
        devs = getattr(x, "devices", None)
        if callable(devs):
            return next(iter(devs())).platform == "tpu"
    except Exception:
        pass
    return jax.default_backend() == "tpu"
