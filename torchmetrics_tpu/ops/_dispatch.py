"""Shared dispatch helpers for the fused-op gates."""

from __future__ import annotations

import jax


def default_backend() -> str:
    """Best-effort default JAX backend platform name.

    Shared by the fused-op gates and the update engine's enablement logic
    (``engine/config.py``): a backend-init failure must degrade to the eager
    CPU path, never propagate out of a dispatch decision.
    """
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def inputs_on_tpu(x) -> bool:
    """Whether ``x`` lives on (or will be placed on) a TPU.

    Dispatch on the concrete committed device when available — explicit placement
    on a non-default backend must pick the matching path — falling back to the
    default backend for tracers, whose device is unknown at trace time.
    """
    try:
        devs = getattr(x, "devices", None)
        if callable(devs):
            return next(iter(devs())).platform == "tpu"
    except Exception:
        pass
    return jax.default_backend() == "tpu"
