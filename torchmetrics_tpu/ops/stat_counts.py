"""Fused logits -> per-class stat-scores — the accuracy-family hot op.

The staged pipeline (reference ``functional/classification/stat_scores.py:319-411``)
costs ~3 HBM passes over the ``(N, C)`` logits at large ``C`` plus a scatter-add into
a ``(C, C)`` confusion matrix and its dense reductions. Both fused implementations
here skip the confusion matrix entirely and reduce straight to three ``(C,)``
counters:

    pred_count[c] = #{n : argmax(logits[n]) == c and valid[n]}
    tp[c]         = #{n : argmax(logits[n]) == c == target[n] and valid[n]}
    tgt_count[c]  = #{n : target[n] == c and valid[n]}

fp/fn/tn follow arithmetically (fp = pred_count - tp, fn = tgt_count - tp,
tn = n_valid - tp - fp - fn with n_valid = Σ tgt_count). 0/1 weights are exact in
bf16 and the f32 accumulators are exact below 2**24, so counts are bit-identical to
the integer path.

Two implementations:

- ``impl="onehot_matmul"`` (default on every backend): plain XLA — argmax, then two
  MXU matmuls whose bf16 one-hot operands (``iota == label``) XLA generates lazily
  inside the matmul fusion, so the only HBM traffic is the single logits read.
- ``impl="pallas"``: the explicit-pipeline Mosaic kernel (same algorithm per block).

Measured on TPU v5 lite at 8192x1000 (scan-slope, carry probe on the int target so no
input-copy tax, best of 5): staged 122.7 µs, pallas 154.7 µs, onehot_matmul
**46.6 µs** — ~88% of the 41 µs one-pass HBM floor and 2.6x over staged. The pallas
version loses because its explicit VMEM block pipeline re-materialises the one-hot
stripes that XLA's operand fusion never writes anywhere; it is kept for the
interpret-mode test oracle and as the template for ops the compiler cannot fuse.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

try:  # pallas needs a recent jaxlib; fall back silently if absent
    from jax.experimental import pallas as pl

    _PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    _PALLAS_AVAILABLE = False

_VMEM_BUDGET = 6 * 2**20
_EXACT_F32_LIMIT = 1 << 24
# honest ceiling: _block_rows() hits 0 near C~6000 under the VMEM budget
_MAX_CLASSES = 4096


def _kernel(lg_ref, tgt_ref, out_ref):
    """One row block: row-max one-hot + two MXU matmuls, everything 2-D for Mosaic.

    lg (B, C) f32 logits; tgt (B, 1) i32 target with invalid rows pre-mapped to -1;
    out (C, 8) f32 accumulator — columns [tp, pred_count, tgt_count, 0...].
    """
    i = pl.program_id(0)
    block, num_classes = lg_ref.shape
    lg = lg_ref[...]
    tgt = tgt_ref[...]  # (B, 1)
    ci = jax.lax.broadcasted_iota(jnp.int32, (block, num_classes), 1)
    rowmax = jnp.max(lg, axis=1, keepdims=True)  # (B, 1)
    # first-occurrence tie-break == jnp.argmax: min column index attaining the max.
    # NaN handling also matches jnp.argmax (NaN is treated as maximal): a NaN row-max
    # fails every equality, so substitute the first NaN's index for those rows.
    am = jnp.min(jnp.where(lg == rowmax, ci, num_classes), axis=1, keepdims=True)  # (B, 1)
    first_nan = jnp.min(jnp.where(jnp.isnan(lg), ci, num_classes), axis=1, keepdims=True)
    am = jnp.minimum(first_nan, am)
    # out-of-range targets behave like the staged path's scatter mode='drop':
    # the whole sample is ignored
    valid = ((tgt >= 0) & (tgt < num_classes)).astype(jnp.bfloat16)  # (B, 1)
    correct = jnp.where(am == tgt, valid, jnp.bfloat16(0))  # (B, 1)
    pred_oh = (ci == am).astype(jnp.bfloat16)  # (B, C)
    tgt_oh = (ci == tgt).astype(jnp.bfloat16)  # (B, C); -1 matches nothing
    # (B, 8) weight columns: [correct, valid, 0...]
    w = jnp.concatenate([correct, valid, jnp.zeros((block, 6), jnp.bfloat16)], axis=1)
    dims = (((0,), (0,)), ((), ()))  # contract over the B rows
    part = jax.lax.dot_general(pred_oh, w, dims, preferred_element_type=jnp.float32)  # (C, 8)
    tgt_part = jax.lax.dot_general(tgt_oh, valid, dims, preferred_element_type=jnp.float32)  # (C, 1)
    # place tgt_count into column 2 via a lane mask (scatter doesn't lower in Mosaic)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, part.shape, 1)
    part = jnp.where(col_iota == 2, tgt_part, part)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += part


def _block_rows(num_classes: int) -> int:
    """Rows per block so logits + two one-hot stripes fit the VMEM budget."""
    bytes_per_row = 4 * num_classes + 2 * 2 * num_classes + 32
    out_bytes = num_classes * 8 * 4
    budget = _VMEM_BUDGET - out_bytes
    if budget <= 0:
        return 0
    rows = min(budget // bytes_per_row, 4096)
    # conservative 128-alignment keeps the (rows, C) logits and (rows, 1) target
    # blocks tileable for any Mosaic layout choice (sublane needs 8, lane 128)
    return (rows // 128) * 128


@functools.partial(jax.jit, static_argnames=("num_classes", "interpret"))
def _fused_counts_pallas(
    preds: Array, target: Array, num_classes: int, interpret: bool = False
) -> Tuple[Array, Array, Array]:
    """(tp, pred_count, tgt_count), each (C,) int32. ``target`` uses -1 for invalid."""
    n = preds.shape[0]
    if n == 0:
        # a zero-length grid would leave the output buffer unwritten
        zeros = jnp.zeros(num_classes, jnp.int32)
        return zeros, zeros, zeros
    blk = _block_rows(num_classes)
    if blk == 0:
        raise ValueError(
            f"num_classes={num_classes} exceeds the kernel's VMEM budget; use the staged"
            " format/update pipeline (the dispatch gate does this automatically)."
        )
    pad = (-n) % blk
    if pad:
        preds = jnp.pad(preds, ((0, pad), (0, 0)))
        target = jnp.pad(target, (0, pad), constant_values=-1)
    nrows = preds.shape[0]
    out = pl.pallas_call(
        _kernel,
        grid=(nrows // blk,),
        in_specs=[
            pl.BlockSpec((blk, num_classes), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((num_classes, 8), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_classes, 8), jnp.float32),
        interpret=interpret,
    )(preds.astype(jnp.float32), target.astype(jnp.int32).reshape(nrows, 1))
    out = out.astype(jnp.int32)
    return out[:, 0], out[:, 1], out[:, 2]


def _counts_onehot_matmul(preds: Array, target: Array, num_classes: int) -> Tuple[Array, Array, Array]:
    """(tp, pred_count, tgt_count) via two MXU matmuls — no confusion matrix, no scatter.

    The bf16 one-hot operands are ``iota == label`` comparisons that XLA generates
    inside the matmul fusion (never written to HBM), so total traffic is the single
    logits read of the argmax. ``target`` uses -1 for invalid rows.
    """
    am = jnp.argmax(preds, axis=-1).astype(jnp.int32)
    valid = ((target >= 0) & (target < num_classes)).astype(jnp.bfloat16)
    correct = jnp.where(am == target, valid, jnp.bfloat16(0))
    ci = jnp.arange(num_classes, dtype=jnp.int32)
    tgt_oh = (target[:, None] == ci).astype(jnp.bfloat16)  # (N, C); -1 matches nothing
    pred_oh = (am[:, None] == ci).astype(jnp.bfloat16)  # invalid rows zeroed by the valid weight
    w = jnp.stack([correct, valid], axis=1)  # (N, 2)
    dims = (((0,), (0,)), ((), ()))  # contract over the N rows
    tt = jax.lax.dot_general(tgt_oh, w, dims, preferred_element_type=jnp.float32)  # (C, 2)
    pc = jax.lax.dot_general(pred_oh, valid[:, None], dims, preferred_element_type=jnp.float32)  # (C, 1)
    return (
        tt[:, 0].astype(jnp.int32),
        pc[:, 0].astype(jnp.int32),
        tt[:, 1].astype(jnp.int32),
    )


def fused_multiclass_stat_scores_supported(
    preds: Array, target: Array, num_classes: int, top_k: int, multidim_average: str
) -> bool:
    """Gate for the fused path: 2-D float logits of width ``num_classes``, top-1,
    global accumulation, counts exact in f32, TPU backend (committed device when
    known). The default onehot-matmul impl has no VMEM class cap — only the pallas
    impl does, and it enforces its own."""
    if top_k != 1 or multidim_average != "global":
        return False
    if preds.ndim != 2 or target.ndim != 1 or not jnp.issubdtype(preds.dtype, jnp.floating):
        return False
    # with validate_args=False a mismatched logits width must fall back to the
    # staged path's argmax semantics rather than mis-slice here
    if preds.shape[1] != num_classes:
        return False
    # per-class f32 accumulator counts are bounded by the number of rows
    if preds.shape[0] >= _EXACT_F32_LIMIT:
        return False
    from torchmetrics_tpu.ops._dispatch import inputs_on_tpu

    return inputs_on_tpu(preds)


def fused_multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    interpret: bool = False,
    impl: Optional[str] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Single-pass (tp, fp, tn, fn), each (C,) int32, from raw logits.

    Matches ``_multiclass_stat_scores_format`` (argmax) +
    ``_multiclass_stat_scores_update`` (confusion-matrix path) exactly.

    ``impl`` is ``"onehot_matmul"`` (default — fastest measured, see module
    docstring) or ``"pallas"``; ``interpret=True`` implies the pallas impl since
    interpret mode exists to exercise that kernel off-TPU.
    """
    if impl is None:
        impl = "pallas" if interpret else "onehot_matmul"
    target = jnp.asarray(target, dtype=jnp.int32)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, jnp.int32(-1), target)
    if impl == "pallas":
        if not _PALLAS_AVAILABLE:
            raise RuntimeError("pallas impl requested but pallas is unavailable")
        tp, pred_count, tgt_count = _fused_counts_pallas(preds, target, num_classes, interpret=interpret)
    elif impl == "onehot_matmul":
        tp, pred_count, tgt_count = _counts_onehot_matmul(jnp.asarray(preds), target, num_classes)
    else:
        raise ValueError(f"unknown impl {impl!r}; expected 'onehot_matmul' or 'pallas'")
    fp = pred_count - tp
    fn = tgt_count - tp
    tn = jnp.sum(tgt_count) - (tp + fp + fn)
    return tp, fp, tn, fn
