"""The Metric kernel — stateful metric base on JAX/XLA.

Capability parity: reference ``src/torchmetrics/metric.py`` (1,133 LoC). Same public
surface (``add_state``, ``forward``, ``update``/``compute``, ``reset``, ``sync`` /
``unsync`` / ``sync_context``, ``clone``, ``persistent``, ``state_dict``, ``set_dtype``,
operator overloads → ``CompositionalMetric``), re-designed TPU-first:

* **State is a pytree of ``jax.Array``s** (plus host-managed lists of arrays for
  unbounded "cat" states, matching the reference's list states). Arrays are immutable,
  so the reference's cache/restore dances (``metric.py:273-354``, ``:482-507``) become
  cheap dict copies of array references — no deep copies, no device round-trips.
* **``merge_state`` is a first-class primitive**: the reference's private
  ``_reduce_states`` (``metric.py:356-384``) is promoted to the core accumulation
  operator; ``forward``'s fast path and cross-chip sync are both folds of it.
* **Sync maps to XLA collectives**: sum/mean/max/min states could use one all-reduce;
  like the reference we gather-then-reduce by default to also support
  ``dist_reduce_fx=None`` raw stacked states (Pearson/retrieval/mAP), pluggable via
  ``dist_sync_fn``. See ``parallel/sync.py``.
* **No grad toggling** — JAX differentiation is functional (``jax.grad`` over the
  functional twins); ``is_differentiable`` metadata is kept for parity.
"""

from __future__ import annotations

import functools
import inspect
from contextlib import contextmanager
from copy import deepcopy
from time import perf_counter
from typing import Any, Callable, Dict, Generator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.diag import hist as _hist
from torchmetrics_tpu.diag import profile as _profile
from torchmetrics_tpu.diag import trace as _diag
from torchmetrics_tpu.parallel.sync import gather_all_tensors, jit_distributed_available
from torchmetrics_tpu.utilities.data import (
    _flatten,
    _squeeze_if_scalar,
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
)
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _is_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, jnp.ndarray)) and not isinstance(x, (list, tuple))


class Metric:
    """Base class for all metrics (reference ``metric.py:60-...``).

    Standard flow::

        acc = MulticlassAccuracy(num_classes=5)
        for preds, target in loader:
            batch_acc = acc(preds, target)   # forward: batch value + accumulation
        total = acc.compute()                # epoch value, synced across chips

    Args (all via ``**kwargs``, unknown kwargs raise — reference ``metric.py:141-143``):
        compute_on_cpu: move list states to host after update (ref ``metric.py:108``).
        dist_sync_on_step: sync state every ``forward`` (expensive; ref ``:114``).
        process_group: sub-world to sync over — for us a mesh-axis name or process
            subset handed to ``dist_sync_fn`` (ref ``:120``).
        dist_sync_fn: custom ``(tensor, group) -> list[tensor]`` gather (ref ``:122``).
        distributed_available_fn: predicate for "is distributed" (ref ``:128``).
        sync_on_compute: sync automatically inside ``compute`` (ref ``:130``).
        compute_with_cache: cache computed value until next update/reset (ref ``:135``).
    """

    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = None
    plot_lower_bound: Optional[float] = None
    plot_upper_bound: Optional[float] = None
    plot_legend_name: Optional[str] = None
    #: state names whose VALUES must be identical on every rank (constants,
    #: threshold tables, …). The opt-in divergence audit
    #: (``torchmetrics_tpu.diag.audit_context`` / ``TORCHMETRICS_TPU_AUDIT=1``)
    #: fingerprints these during the packed sync's metadata exchange and flags
    #: cross-rank divergence before the fold corrupts them.
    _rank_invariant_states: frozenset = frozenset()

    def __init__(self, **kwargs: Any) -> None:
        self._device = None
        self._dtype = jnp.float32

        self.compute_on_cpu = kwargs.pop("compute_on_cpu", False)
        if not isinstance(self.compute_on_cpu, bool):
            raise ValueError(f"Expected keyword argument `compute_on_cpu` to be a `bool` but got {self.compute_on_cpu}")

        self.dist_sync_on_step = kwargs.pop("dist_sync_on_step", False)
        if not isinstance(self.dist_sync_on_step, bool):
            raise ValueError(
                f"Expected keyword argument `dist_sync_on_step` to be a `bool` but got {self.dist_sync_on_step}"
            )

        self.process_group = kwargs.pop("process_group", None)

        self.dist_sync_fn = kwargs.pop("dist_sync_fn", None)
        if self.dist_sync_fn is not None and not callable(self.dist_sync_fn):
            raise ValueError(
                f"Expected keyword argument `dist_sync_fn` to be an callable function but got {self.dist_sync_fn}"
            )

        self.distributed_available_fn = kwargs.pop("distributed_available_fn", None) or jit_distributed_available

        self.sync_on_compute = kwargs.pop("sync_on_compute", True)
        if not isinstance(self.sync_on_compute, bool):
            raise ValueError(
                f"Expected keyword argument `sync_on_compute` to be a `bool` but got {self.sync_on_compute}"
            )
        self.compute_with_cache = kwargs.pop("compute_with_cache", True)
        if not isinstance(self.compute_with_cache, bool):
            raise ValueError(
                f"Expected keyword argument `compute_with_cache` to be a `bool` but got {self.compute_with_cache}"
            )
        # fused update engine (engine/): None = follow the process-wide policy
        # (auto-on when the default backend is an accelerator), True/False forces
        self.compiled_update = kwargs.pop("compiled_update", None)
        if self.compiled_update is not None and not isinstance(self.compiled_update, bool):
            raise ValueError(
                f"Expected keyword argument `compiled_update` to be a `bool` or `None` but got {self.compiled_update}"
            )
        # multi-step scan dispatch (engine/scan.py): None = follow the
        # process-wide policy (TORCHMETRICS_TPU_SCAN / scan_context), 0/False
        # forces the queue off for this metric, an int K >= 2 forces depth K
        self.scan_steps = kwargs.pop("scan_steps", None)
        if self.scan_steps is not None:
            from torchmetrics_tpu.engine.scan import coerce_k

            self.scan_steps = coerce_k(self.scan_steps)
        # async pipelined dispatch (engine/async_dispatch.py): None = follow
        # the process-wide policy (TORCHMETRICS_TPU_ASYNC / async_context),
        # False/0 forces background drains off, True/int forces them on with
        # the default/explicit in-flight bound. Layers on the scan queue.
        self.async_dispatch = kwargs.pop("async_dispatch", None)
        if self.async_dispatch is not None:
            from torchmetrics_tpu.engine.async_dispatch import coerce_inflight

            self.async_dispatch = coerce_inflight(self.async_dispatch)

        if kwargs:
            kwargs_ = [f"`{a}`" for a in sorted(kwargs)]
            raise ValueError(f"Unexpected keyword arguments: {', '.join(kwargs_)}")

        # state management
        self._defaults: Dict[str, Union[List, Array]] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Union[str, Callable, None]] = {}
        # first-class role registry (engine/statespec.py): one StateSpec per
        # registered state, installed by add_state — every engine consumes
        # these instead of re-deriving roles from attribute conventions
        self._state_specs: Dict[str, Any] = {}

        self._update_signature = inspect.signature(self.update)
        self.update: Callable = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute: Callable = self._wrap_compute(self.compute)  # type: ignore[method-assign]
        self._computed = None
        self._forward_cache = None
        self._update_count = 0
        self._to_sync = self.sync_on_compute
        self._should_unsync = True
        self._enable_grad = False
        self._dtype_convert = False

        # initialize state
        self._cache: Optional[Dict[str, Union[List[Array], Array]]] = None
        self._is_synced = False
        # per-instance compiled-step cache (engine/compiled.py), created lazily on
        # the first engine-enabled update; never pickled/cloned (rebuilt per process)
        self._engine = None
        # per-instance epoch engine (engine/epoch.py): packed sync + cached
        # compute executables; same lifecycle as _engine
        self._epoch = None
        # dist_reduce_fx=None array states that currently hold a stacked
        # (shards, *default.shape) layout — tracked explicitly so folding never has
        # to guess from ndim (a state whose legitimate per-update shape is one rank
        # above its default would otherwise be mis-concatenated)
        self._none_folded: set = set()

    @property
    def _update_called(self) -> bool:
        return self._update_count > 0

    @property
    def update_called(self) -> bool:
        """Return whether ``update`` / ``forward`` has been called at least once."""
        return self._update_count > 0

    @property
    def update_count(self) -> int:
        """Number of times ``update``/``forward`` has been called since init/reset."""
        return self._update_count

    def add_state(
        self,
        name: str,
        default: Union[list, Array, float, int],
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
        spec: Optional[Any] = None,
    ) -> None:
        """Register a metric state variable (reference ``metric.py:181-247``).

        ``default`` must be an array (any shape) or an empty list (for "cat"-style
        unbounded states). ``dist_reduce_fx`` ∈ {"sum","mean","cat","max","min", None,
        callable} selects how the state folds across chips and across ``forward`` steps.

        ``spec`` declares the state's first-class role
        (:class:`~torchmetrics_tpu.engine.statespec.StateSpec`, or a dict of
        field overrides — e.g. ``{"role": "hh-ids", ...}`` for the
        heavy-hitter pair, ``{"dtype_policy": "count"}`` for counters under
        the ``count_dtype()`` widening contract). Omitted, the spec derives
        from ``dist_reduce_fx`` plus the metric's class-level declarations;
        every engine resolves roles from the registered spec instead of
        re-parsing attribute conventions.
        """
        if not isinstance(default, list) or default:
            if isinstance(default, (int, float)):
                default = jnp.asarray(default, dtype=self._dtype if isinstance(default, float) else None)
            if not _is_array(default):
                raise ValueError("state variable must be a jax array or any empty list (where you can append arrays)")
        if dist_reduce_fx == "sum":
            dist_reduce_fx = dim_zero_sum
        elif dist_reduce_fx == "mean":
            dist_reduce_fx = dim_zero_mean
        elif dist_reduce_fx == "max":
            dist_reduce_fx = dim_zero_max
        elif dist_reduce_fx == "min":
            dist_reduce_fx = dim_zero_min
        elif dist_reduce_fx == "cat":
            dist_reduce_fx = dim_zero_cat
        elif dist_reduce_fx is not None and not callable(dist_reduce_fx):
            raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]")

        if isinstance(default, list):
            setattr(self, name, [])
        else:
            setattr(self, name, default)

        self._defaults[name] = default  # arrays are immutable → no defensive copy needed
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        from torchmetrics_tpu.engine import statespec as _statespec

        spec_obj = _statespec.register_state_spec(
            self, _statespec.build_spec(self, name, dist_reduce_fx, spec)
        )
        if _is_array(default):
            from torchmetrics_tpu.parallel import sharding as _sharding

            needs_place = spec_obj.shard_rule != "replicate" or (
                # the per-state-name partition-rule table (2-D mesh tier) can
                # shard a state whose declared rule is replicate
                _sharding.partition_rules_active()
                and _sharding.match_partition_rule(name, type(self).__name__) is not None
            )
        else:
            needs_place = False
        if needs_place:
            # born distributed (parallel/sharding.py): the registered default
            # itself is placed onto the rule's resolved NamedSharding, so the
            # state never materializes unsharded and reset() restores the
            # sharded default by reference. No active mesh = no-op.
            placed = _sharding.place_state(self, name, default, spec_obj)
            if placed is not default:
                self._defaults[name] = placed
                setattr(self, name, placed)

    def _apply_shard_rules(self) -> None:
        """Re-place rule-carrying states after a host round-trip.

        ``load_state_dict``/unpickling hand back single-device arrays; when a
        state mesh is active the registered shard rules re-apply so restored
        state keeps the born-distributed placement. Cheap no-op for the
        common case (no non-replicate rules registered, or no active mesh).
        """
        specs = self.__dict__.get("_state_specs") or {}
        from torchmetrics_tpu.parallel import sharding as _sharding

        if not any(
            getattr(sp, "shard_rule", "replicate") != "replicate" for sp in specs.values()
        ) and not _sharding.partition_rules_active():
            return
        _sharding.reshard_states(self)

    def state_specs(self) -> Dict[str, Any]:
        """Every registered state's :class:`~torchmetrics_tpu.engine.statespec.
        StateSpec`, in registration order (missing entries derive from the
        deprecated attribute conventions, counted as ``spec_fallbacks``)."""
        from torchmetrics_tpu.engine import statespec as _statespec

        return _statespec.specs_of(self, consumer="state_specs")

    # ------------------------------------------------------------------ forward

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate batch into global state AND return the batch value (reference ``metric.py:252-271``)."""
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric shouldn't be synced when performing ``forward``. HINT: Did you forget to call ``unsync``?"
            )
        # forward returns a value, so it is a state observation: pending scan
        # payloads fold in first, and forward's own updates bypass the queue
        self._drain_scan("observation:forward")
        from torchmetrics_tpu.engine import txn as _txn

        # mutation guard for preemption-safe snapshots: a signal handler must
        # not persist state mid-mutation (forward folds outside the update
        # wrapper, hence the depth covers the whole call)
        self._mutation_depth = getattr(self, "_mutation_depth", 0) + 1
        try:
            return self._forward_guarded(_txn, *args, **kwargs)
        finally:
            self._mutation_depth -= 1

    def _forward_guarded(self, _txn: Any, *args: Any, **kwargs: Any) -> Any:
        if (
            self.full_state_update
            or self.full_state_update is None
            or self.dist_sync_on_step
            or _txn.quarantine_enabled()
        ):
            # quarantine forces the full-state path: its global update gets the
            # exact in-graph select and the throwaway batch state is restored
            # wholesale, whereas the reduce path's count-weighted mean fold
            # would dilute the global state by every quarantined batch (the
            # host-side weights cannot see the device poison flag without a
            # hot-loop transfer)
            self._forward_cache = self._forward_full_state_update(*args, **kwargs)
        else:
            self._forward_cache = self._forward_reduce_state_update(*args, **kwargs)
        return self._forward_cache

    @contextmanager
    def _batch_value_context(self) -> Generator:
        """Shared sync/cache guard for forward's batch-value compute.

        Both forward paths need the same dance: sync only when
        ``dist_sync_on_step`` asks for it, never auto-unsync mid-forward, and
        keep ``compute_on_cpu`` from moving the throwaway batch state to host —
        then restore every flag and invalidate the computed cache. Previously
        copied verbatim into both paths (and drifted once); one guard now.
        """
        self._to_sync = self.dist_sync_on_step
        self._should_unsync = False
        _temp_compute_on_cpu = self.compute_on_cpu
        self.compute_on_cpu = False
        # the window's compute() runs on THROWAWAY single-batch state (reset
        # zeroed counter and count alike) — epoch-level quarantine surfacing
        # in the compute wrapper must not fire here
        self._in_batch_value = True
        try:
            yield
        finally:
            self._in_batch_value = False
            self._is_synced = False
            self._should_unsync = True
            self._to_sync = self.sync_on_compute
            self._computed = None
            self.compute_on_cpu = _temp_compute_on_cpu

    def _forward_full_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Safe two-``update`` forward path (reference ``metric.py:273-315``).

        With immutable arrays, caching the global state is a dict copy of references —
        the second update on reset state cannot corrupt the cached arrays.
        """
        self.update(*args, **kwargs)
        _update_count = self._update_count

        with self._batch_value_context():
            cache = self._copy_state_refs()
            self.reset()
            self.update(*args, **kwargs)
            batch_val = self.compute()
            self._restore_state_refs(cache)
            self._update_count = _update_count

        return batch_val

    def _forward_reduce_state_update(self, *args: Any, **kwargs: Any) -> Any:
        """Fast single-``update`` forward path (reference ``metric.py:317-354``)."""
        global_state = self._copy_state_refs()
        _update_count = self._update_count
        self.reset()

        with self._batch_value_context():
            self.update(*args, **kwargs)
            batch_val = self.compute()
            self._update_count = _update_count + 1
            self._reduce_states(global_state)

        return batch_val

    def _copy_state_refs(self) -> Dict[str, Any]:
        refs: Dict[str, Any] = {
            attr: (list(v) if isinstance(v := getattr(self, attr), list) else v) for attr in self._defaults
        }
        refs["__none_folded__"] = frozenset(self._none_folded)
        # the quarantine counter rides sync/forward snapshots like a state: a
        # packed sync SUMS it cross-rank (parallel/packing.py), so unsync must
        # restore the local count or the next sync would re-sum a sum
        if "_quarantined_count" in self.__dict__:
            refs["_quarantined_count"] = self.__dict__["_quarantined_count"]
            refs["_quarantine_reported"] = self.__dict__.get("_quarantine_reported", 0)
        # the compensation residual dict rides too: a packed sync two-sum-folds
        # it cross-rank (parallel/packing.py), so unsync must restore the local
        # (value, residual) pair or the next sync would re-fold a fold
        if "_comp_residuals" in self.__dict__:
            refs["_comp_residuals"] = dict(self.__dict__["_comp_residuals"])
        return refs

    def _restore_state_refs(self, cache: Dict[str, Any]) -> None:
        # a reported-watermark change between snapshot and restore means a
        # sanctioned quarantine read surfaced the WORLD total inside this sync
        # window — see txn.mark_reported for why the restored local count must
        # then be treated as already reported
        read_in_window = (
            "_quarantine_reported" in cache
            and self.__dict__.get("_quarantine_reported", 0) != cache["_quarantine_reported"]
        )
        for attr, val in cache.items():
            if attr == "__none_folded__":
                self._none_folded = set(val)
            else:
                setattr(self, attr, val)
        if read_in_window:
            from torchmetrics_tpu.engine import txn as _txn

            _txn.mark_reported(self)

    def merge_state(self, incoming_state: Union["Metric", Dict[str, Any]], incoming_count: int = 1) -> None:
        """Fold another metric's state (or a raw state dict) into this one.

        TPU-first promotion of the reference's private ``_reduce_states``
        (``metric.py:356-384``) to a public primitive for map-reduce-style eval
        pipelines. Mean states are weighted by update counts (taken from the incoming
        metric, or ``incoming_count`` for raw dicts).
        """
        from torchmetrics_tpu.engine import numerics as _numerics

        # both sides of the fold are observed: drain pending scan payloads
        self._drain_scan("observation:merge_state")
        if isinstance(incoming_state, Metric):
            incoming_state._drain_scan("observation:merge_state")
        incoming_folded: Optional[frozenset] = None  # raw dicts: unknown -> ndim fallback
        if isinstance(incoming_state, Metric):
            # host-side counts fold as Python ints (arbitrary precision): a
            # numpy int32 count from a wrapper or checkpoint would WRAP
            # silently near 2**31 under numpy arithmetic
            incoming_count = _numerics.py_count(incoming_state._update_count)
            incoming_folded = frozenset(incoming_state._none_folded)
            incoming_quarantined = incoming_state.__dict__.get("_quarantined_count")
            incoming_q_reported = incoming_state.__dict__.get("_quarantine_reported", 0)
            incoming_res = dict(incoming_state.__dict__.get("_comp_residuals") or {})
            incoming_state = {attr: getattr(incoming_state, attr) for attr in incoming_state._defaults}
        else:
            incoming_count = _numerics.py_count(incoming_count)
            incoming_quarantined = incoming_state.get("_quarantined_count")
            incoming_q_reported = incoming_state.get("_quarantine_reported", 0)
            incoming_res = dict(incoming_state.get("_comp_residuals") or {})
        self_count = _numerics.py_count(self._update_count)
        self_res = self.__dict__.get("_comp_residuals") or {}
        merged_res: Dict[str, Any] = dict(self_res)
        for attr in self._defaults:
            self_state = getattr(self, attr)
            other_state = incoming_state[attr]
            reduce_fn = self._reductions[attr]
            if reduce_fn == dim_zero_sum:
                if attr in self_res or attr in incoming_res:
                    # compensated shards fold via two-sum: residuals add, the
                    # values' exact fold error joins the merged residual
                    reduced, err = _numerics.two_sum(self_state, other_state)
                    merged_res[attr] = (
                        self_res.get(attr, jnp.zeros_like(reduced))
                        + incoming_res.get(attr, jnp.zeros_like(reduced))
                        + err
                    )
                else:
                    reduced = _safe_int_sum(self_state, other_state)
            elif reduce_fn == dim_zero_mean:
                total = max(self_count + incoming_count, 1)
                if attr in self_res or attr in incoming_res:
                    # mean-reduced compensated state: residuals fold with the
                    # SAME count weighting as the values — keeping the stale
                    # local residual (or dropping the incoming one) would make
                    # the next reanchor fold a correction belonging to a
                    # different accumulator
                    reduced = (self_count * self_state + incoming_count * other_state) / total
                    merged_res[attr] = (
                        self_count * self_res.get(attr, jnp.zeros_like(reduced))
                        + incoming_count * incoming_res.get(attr, jnp.zeros_like(reduced))
                    ) / total
                else:
                    reduced = (self_count * self_state + incoming_count * other_state) / total
            elif reduce_fn == dim_zero_max:
                reduced = jnp.maximum(self_state, other_state)
            elif reduce_fn == dim_zero_min:
                reduced = jnp.minimum(self_state, other_state)
            elif reduce_fn == dim_zero_cat:
                reduced = (list(self_state) if isinstance(self_state, list) else [self_state]) + (
                    list(other_state) if isinstance(other_state, list) else [other_state]
                )
            elif reduce_fn is None and _is_array(self_state):
                reduced = self._fold_none_arrays(
                    attr,
                    self_state,
                    other_state,
                    self_folded=attr in self._none_folded,
                    other_folded=None if incoming_folded is None else attr in incoming_folded,
                )
            elif reduce_fn is None and isinstance(self_state, list):
                reduced = _flatten([self_state, other_state])
            elif reduce_fn and callable(reduce_fn):
                reduced = reduce_fn(jnp.stack([self_state, other_state]))
            else:
                raise TypeError(f"Unsupported reduce_fn: {reduce_fn}")
            setattr(self, attr, reduced)
        self._update_count = self_count + incoming_count
        if self_res or incoming_res:
            self._comp_residuals = merged_res
        if incoming_quarantined is not None:
            from torchmetrics_tpu.engine import txn as _txn

            # map-reduce folds are additive in the counter AND the reported
            # watermark: each side's already-surfaced batches stay surfaced,
            # each side's unreported delta stays pending exactly once
            local_quarantined = _txn.ensure_count(self)
            self._quarantined_count = local_quarantined + incoming_quarantined
            self._quarantine_reported = self.__dict__.get("_quarantine_reported", 0) + incoming_q_reported
        self._computed = None

    def _fold_none_arrays(
        self,
        attr: str,
        self_state: Any,
        other_state: Any,
        self_folded: Optional[bool] = None,
        other_folded: Optional[bool] = None,
    ) -> Any:
        """N-way fold of a ``dist_reduce_fx=None`` array state.

        Raw-gathered states keep a stacked ``(shards, *default.shape)`` layout (the
        reference stacks gathered tensors, ``metric.py:401-416``); appending rows —
        rather than pairwise ``jnp.stack`` — keeps folding associative so three or
        more shards can be merged sequentially. Whether a side already carries the
        stacked shard axis is tracked EXPLICITLY (``_none_folded`` on each metric,
        threaded through the callers) — only raw state dicts, whose provenance is
        unknown, fall back to the ndim heuristic — so a state whose legitimate
        per-update shape is one rank above its default still merges with ``stack``
        semantics.
        """
        base_ndim = getattr(self._defaults[attr], "ndim", 0)

        def _rows(x: Any, folded: Optional[bool]) -> Any:
            x = jnp.asarray(x)
            if folded is None:  # unknown provenance: infer — documented fallback only
                folded = x.ndim == base_ndim + 1
            return x if folded else x[None]

        out = jnp.concatenate(
            [_rows(self_state, self_folded), _rows(other_state, other_folded)], axis=0
        )
        self._none_folded.add(attr)
        return out

    def _reduce_states(self, incoming_state: Dict[str, Any]) -> None:
        """Merge ``incoming_state`` (treated as global) with current (batch) state (reference ``metric.py:356-384``)."""
        from torchmetrics_tpu.engine import numerics as _numerics

        global_folded = incoming_state.get("__none_folded__")  # _copy_state_refs snapshots carry this
        global_res = incoming_state.get("_comp_residuals") or {}
        local_res = self.__dict__.get("_comp_residuals") or {}
        merged_res: Dict[str, Any] = dict(local_res)
        for attr in self._defaults:
            local_state = getattr(self, attr)
            global_state = incoming_state[attr]
            reduce_fn = self._reductions[attr]
            if reduce_fn == dim_zero_sum:
                if attr in global_res or attr in local_res:
                    # compensated forward fold: the global (value, residual)
                    # absorbs the batch contribution through the same two-sum
                    # the compiled step uses — residual fed back, error exact
                    reduced, err = _numerics.two_sum(
                        global_state,
                        local_state
                        + global_res.get(attr, jnp.zeros_like(global_state))
                        + local_res.get(attr, jnp.zeros_like(global_state)),
                    )
                    merged_res[attr] = err
                else:
                    # same overflow-safe widening merge_state applies: forward's
                    # reduce path folds the identical sum states
                    reduced = _safe_int_sum(global_state, local_state)
            elif reduce_fn == dim_zero_mean:
                reduced = ((self._update_count - 1) * global_state + local_state) / self._update_count
                if attr in global_res or attr in local_res:
                    # count-weighted residual fold, mirroring the value fold
                    merged_res[attr] = (
                        (self._update_count - 1) * global_res.get(attr, jnp.zeros_like(reduced))
                        + local_res.get(attr, jnp.zeros_like(reduced))
                    ) / self._update_count
            elif reduce_fn == dim_zero_max:
                reduced = jnp.maximum(global_state, local_state)
            elif reduce_fn == dim_zero_min:
                reduced = jnp.minimum(global_state, local_state)
            elif reduce_fn == dim_zero_cat:
                reduced = (list(global_state) if isinstance(global_state, list) else [global_state]) + (
                    list(local_state) if isinstance(local_state, list) else [local_state]
                )
            elif reduce_fn is None and _is_array(global_state):
                reduced = self._fold_none_arrays(
                    attr,
                    global_state,
                    local_state,
                    self_folded=None if global_folded is None else attr in global_folded,
                    other_folded=attr in self._none_folded,
                )
            elif reduce_fn is None and isinstance(global_state, list):
                reduced = _flatten([global_state, local_state])
            elif reduce_fn and callable(reduce_fn):
                reduced = reduce_fn(jnp.stack([global_state, local_state]))
            else:
                raise TypeError(f"Unsupported reduce_fn: {reduce_fn}")
            setattr(self, attr, reduced)
        if global_res or local_res:
            self._comp_residuals = merged_res
        # forward's reduce path reset the quarantine counter with the states;
        # fold the snapshotted global count back in (the counter is additive)
        global_quarantined = incoming_state.get("_quarantined_count")
        local_quarantined = self.__dict__.get("_quarantined_count")
        if global_quarantined is not None and local_quarantined is not None:
            self._quarantined_count = global_quarantined + local_quarantined
            self._quarantine_reported = incoming_state.get("_quarantine_reported", 0)

    # ------------------------------------------------------------------ sync

    def _sync_dist(self, dist_sync_fn: Callable = gather_all_tensors, process_group: Optional[Any] = None) -> None:
        """Gather every state from all chips/processes and apply its reduction (reference ``metric.py:386-416``)."""
        from torchmetrics_tpu.parallel.sharding import is_sharded, spans_processes

        # live-sharded states are global by construction: the SPMD executable
        # already folded every device's contribution through in-graph
        # collectives, and gathering a partitioned buffer through the host
        # would read shards this process may not even address — skip them,
        # mirroring the packed plan's gather_skipped semantics
        sharded_attrs = {attr for attr in self._reductions if is_sharded(getattr(self, attr))}
        if sharded_attrs and jax.process_count() > 1 and any(
            not spans_processes(getattr(self, attr)) for attr in sharded_attrs
        ):
            # same multi-host honesty warning as the packed path: a
            # process-local mesh folded only this process's contributions
            rank_zero_warn(
                "Sharded metric state on a process-local mesh skipped a"
                f" {jax.process_count()}-process sync: the in-graph collectives"
                " folded only THIS process's contributions. Build the state mesh"
                " over the global device set for multi-host sharding.",
                UserWarning,
            )
        input_dict = {
            attr: getattr(self, attr) for attr in self._reductions if attr not in sharded_attrs
        }

        for attr, reduction_fn in self._reductions.items():
            if attr in sharded_attrs:
                continue
            # pre-concatenate list states to minimize collectives (ref ``metric.py:391-392``)
            if reduction_fn == dim_zero_cat and isinstance(input_dict[attr], list) and len(input_dict[attr]) > 1:
                input_dict[attr] = [dim_zero_cat(input_dict[attr])]

        if jax.process_count() > 1:
            # A list state syncs one collective PER ELEMENT, so ranks holding different
            # list lengths enter different numbers of collectives — a silent deadlock.
            # Cat states are pre-concatenated above (length is 0 or 1, so only
            # empty-vs-nonempty can diverge); None-reduced list states (detection's
            # packed per-batch states) keep their elements separate and positional, so
            # ANY length mismatch is fatal, not just mixed emptiness. ONE tiny
            # fixed-shape count gather covering every list state at once (every rank
            # participates; attr order is the shared _reductions insertion order)
            # fails loud ON EVERY RANK before the ragged collectives can wedge.
            # filter on the DEFAULT's type, not the live local type: a None-reduced
            # state folded to an array on one rank but not another would otherwise
            # make the guard collective itself ragged across ranks
            list_attrs = [
                attr
                for attr, fn in self._reductions.items()
                if (fn == dim_zero_cat or fn is None) and isinstance(self._defaults[attr], list)
            ]
            if list_attrs:
                import zlib

                from torchmetrics_tpu.parallel.sync import _bounded_allgather

                def _shape_fingerprint(x: Any) -> int:
                    """Stable digest of the per-element shapes of a list state.

                    Equal counts do NOT imply matching collectives: None-reduced
                    list states sync one collective PER ELEMENT, so ranks holding
                    the same number of elements with different per-position
                    shapes (e.g. differing final packed-batch sizes) still enter
                    shape-ragged collectives that can crash or wedge the world.
                    crc32 over the flattened (rank, *dims) sequence is
                    process-stable (unlike ``hash``) and rides in the same
                    fixed-shape probe as the counts.
                    """
                    elements = x if isinstance(x, list) else [x]
                    dims: List[int] = []
                    for el in elements:
                        shp = tuple(getattr(el, "shape", ()))
                        dims.append(len(shp))
                        dims.extend(int(d) for d in shp)
                    # mask to a positive int32 so the probe array never depends on
                    # the x64 flag (crc32 is uint32; int64 would truncate without x64)
                    return zlib.crc32(np.asarray(dims, dtype=np.int64).tobytes()) & 0x7FFFFFFF

                # per attr: [count, shape fingerprint]. count = number of collectives
                # this rank will enter (a state folded to a single array enters one);
                # ONE fixed-shape gather covers both probes for every list state.
                local_probe = jnp.asarray(
                    [
                        [len(x) if isinstance(x, list) else 1, _shape_fingerprint(x)]
                        for x in (input_dict[a] for a in list_attrs)
                    ],
                    dtype=jnp.int32,
                )
                # bounded like every other eager collective: the deadlock-guard
                # probe itself must not be able to deadlock
                probe = np.asarray(_bounded_allgather(local_probe, "eager:list-guard"))
                for idx, attr in enumerate(list_attrs):
                    col = probe[:, idx, 0]
                    is_cat = self._reductions[attr] == dim_zero_cat
                    # cat: pre-concat above leaves 0 or 1 elements, so only mixed
                    # emptiness can occur; None: exact positional alignment required.
                    bad = (col.max() > 0 and col.min() == 0) if is_cat else (col.max() != col.min())
                    if bad:
                        raise TorchMetricsUserError(
                            f"Cannot sync list state `{attr}`: processes hold differing"
                            f" element counts {col.tolist()} — ranks with fewer elements"
                            " would skip collectives the rest enter and deadlock the"
                            " world. Ensure every process sees the same number of"
                            " updates before compute(), or skip syncing"
                            " (sync_on_compute=False) for ragged epochs."
                        )
                    fps = probe[:, idx, 1]
                    if not is_cat and fps.max() != fps.min():
                        # equal counts, mismatched per-element shapes: the positional
                        # collectives would be shape-ragged — fail loud on every rank
                        raise TorchMetricsUserError(
                            f"Cannot sync list state `{attr}`: processes hold equal"
                            f" element counts but mismatched per-element shapes"
                            f" (shape fingerprints {fps.tolist()}). Positional"
                            " collectives over a None-reduced list state require"
                            " identical per-position shapes on every rank — e.g."
                            " differing final packed-batch sizes must be padded to a"
                            " common shape before update, or skip syncing"
                            " (sync_on_compute=False)."
                        )

        output_dict = apply_to_collection(
            input_dict,
            (jax.Array, jnp.ndarray),
            dist_sync_fn,
            group=process_group or self.process_group,
        )

        for attr, reduction_fn in self._reductions.items():
            if attr in sharded_attrs:
                continue  # globally consistent already; nothing was gathered
            if isinstance(output_dict[attr], list) and len(output_dict[attr]) == 0:
                setattr(self, attr, [])
                continue
            if _is_array(output_dict[attr][0]):
                output_dict[attr] = jnp.stack(output_dict[attr])
                if reduction_fn is None:
                    # gathered None-reduced arrays now carry a leading shard axis
                    self._none_folded.add(attr)
            elif isinstance(output_dict[attr][0], list) and (
                len(output_dict[attr][0]) == 0 or _is_array(output_dict[attr][0][0])
            ):
                # gathered per-element world lists of arrays -> interleave (ref ``metric.py:400-405``).
                # Host-object entries (RLE dicts, strings) are NOT flattened: the array-only
                # gather can't move them between processes, so per-image alignment must survive.
                output_dict[attr] = _flatten(output_dict[attr])

            if not (callable(reduction_fn) or reduction_fn is None):
                raise TypeError("reduction_fn must be callable or None")
            reduced = reduction_fn(output_dict[attr]) if reduction_fn is not None else output_dict[attr]
            setattr(self, attr, reduced)

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> None:
        """Manually trigger state sync across chips (reference ``metric.py:449-486``)."""
        if self._is_synced and should_sync:
            raise TorchMetricsUserError("The Metric has already been synced.")
        # the exchanged buffers must hold every enqueued step: drain first
        self._drain_scan("observation:sync")

        if distributed_available is None and self.distributed_available_fn is not None:
            distributed_available = self.distributed_available_fn
        is_distributed = distributed_available() if callable(distributed_available) else None

        if not should_sync or not is_distributed:
            return

        if dist_sync_fn is None and self._packed_sync_allowed():
            # fused epoch path: one metadata gather + O(dtypes) collectives for
            # ALL states, fold compiled into one cached executable
            snapshot = self._copy_state_refs()
            with jax.profiler.TraceAnnotation(f"{type(self).__name__}.sync"):
                handled = self._epoch_engine().packed_sync(
                    process_group=process_group or self.process_group
                )
            if handled:
                self._cache = snapshot
                self._is_synced = True
                return
        elif dist_sync_fn is not None and self._epoch_enabled():
            self._epoch_engine().stats.fallback("sync:custom-dist-sync-fn")

        if dist_sync_fn is None:
            dist_sync_fn = gather_all_tensors

        _diag.record("sync.eager", type(self).__name__)
        self._cache = self._copy_state_refs()
        with jax.profiler.TraceAnnotation(f"{type(self).__name__}.sync"):
            self._sync_dist(dist_sync_fn, process_group=process_group)
        self._is_synced = True

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore the pre-sync local state (reference ``metric.py:488-507``)."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise TorchMetricsUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise TorchMetricsUserError("The internal cache should exist to unsync the Metric.")
        self._restore_state_refs(self._cache)
        self._is_synced = False
        self._cache = None

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[Any] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
    ) -> Generator:
        """``sync`` on entry, ``unsync`` on exit (reference ``metric.py:509-543``)."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            process_group=process_group,
            should_sync=should_sync,
            distributed_available=distributed_available,
        )
        try:
            yield
        finally:
            # restore local state even when the compute body raises — otherwise the
            # metric is wedged in the synced state and every later call errors
            self.unsync(should_unsync=self._is_synced and should_unsync)

    # ------------------------------------------------------------------ wrapping

    def _wrap_update(self, update: Callable) -> Callable:
        self._raw_update = update  # unwrapped body — what the engine traces
        # hoisted: the annotation label is rebuilt per step otherwise, and the
        # wrapper is on the hot path of every update (queued or not)
        annotation = f"{type(self).__name__}.update"

        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            from torchmetrics_tpu.engine import txn as _txn

            quarantine_mode = _txn.quarantine_mode()
            if quarantine_mode == _txn.MODE_ERROR:
                # fail-loud admission: raises BEFORE any mutation, so the
                # accumulator AND _update_count are untouched on both paths
                # (unless the enclosing collection step already admitted this
                # exact batch — one blocking sync per metric per step, not two)
                if not self.__dict__.pop("_admission_prechecked", False):
                    _txn.admission_check_or_raise(self, args, kwargs)
            # a snapshot signal handler firing between these mutations would
            # persist a torn shard (count bumped, states mid-write): the depth
            # tells ContinuousSnapshotter to stand on the last completed flush
            self._mutation_depth = getattr(self, "_mutation_depth", 0) + 1
            try:
                self._computed = None
                self._update_count += 1
                # host-side trace span: shows up in jax.profiler / Perfetto timelines so
                # metric updates are attributable inside a profiled training step (SURVEY §5.1)
                with jax.profiler.TraceAnnotation(annotation):
                    if not self._engine_step(args, kwargs):
                        # engine-disabled updates leave no engine counters behind; the
                        # flight-recorder event keeps eager steps visible in the same
                        # timeline as compiled dispatches (engine fallbacks additionally
                        # carry their reason via EngineStats.fallback), timed so the
                        # eager launch cost lands in the same latency histograms
                        rec = _diag.active_recorder()
                        measuring = rec is not None or _profile.active_profile() is not None
                        if not measuring:
                            self._run_eager_update(args, kwargs)
                        else:
                            t0 = perf_counter()
                            self._run_eager_update(args, kwargs)
                            dispatch_us = round((perf_counter() - t0) * 1e6, 3)
                            _hist.observe(type(self).__name__, "eager", "dispatch_us", dispatch_us)
                            if rec is not None:
                                rec.record(
                                    "update.eager", type(self).__name__, dispatch_us=dispatch_us,
                                )
                if self.compute_on_cpu:
                    self._move_list_states_to_cpu()
            finally:
                self._mutation_depth -= 1

        return wrapped_func

    def _run_eager_update(self, args: tuple, kwargs: Dict[str, Any]) -> None:
        """One eager update with full rider parity (compensation + quarantine).

        The engine-off execution of a single batch: the raw body, wrapped in
        the compensated two-sum recomposition and the quarantine
        admission/transactional-skip exactly as the compiled path lowers them
        — shared by the update wrapper's fallback branch and the scan queue's
        step-at-a-time replay (``engine/scan.py``), so the parity logic can
        never drift between the two. Does NOT touch ``_update_count`` or
        ``_computed`` — that is the wrapper's (or the enqueue's) bookkeeping.
        """
        from torchmetrics_tpu.engine import numerics as _numerics
        from torchmetrics_tpu.engine import txn as _txn

        update = self._raw_update
        if _numerics.compensation_active(self):
            # eager parity for the compensated two-sum: the raw body runs on
            # zeroed compensated states and the recomposition matches the
            # compiled transform
            def body() -> None:
                _numerics.eager_update(self, lambda: update(*args, **kwargs))
        else:
            def body() -> None:
                update(*args, **kwargs)
        if _txn.quarantine_mode() == _txn.MODE_QUARANTINE:
            # eager parity: the same admission + transactional skip the
            # compiled path lowers in-graph, so engine-on and engine-off runs
            # agree on quarantined streams
            _txn.eager_update(self, body, args, kwargs)
        else:
            body()

    def _engine_step(self, args: tuple, kwargs: Dict[str, Any]) -> bool:
        """Route one update through the fused engine; False = run eagerly."""
        enabled = self._epoch_enabled()
        k = self._scan_depth() if enabled else None
        queueing = (
            k is not None
            and self._mutation_depth == 1
            and not getattr(self, "_in_batch_value", False)
        )
        eng = self._engine
        if not queueing and eng is not None:
            sq = eng._scan
            if sq is not None and sq.pending:
                # a queue left over from a closed scan scope — OR from the
                # ENGINE itself being disabled mid-stream — drains before this
                # step applies, whatever path it takes (ordering preserved)
                sq.drain("scan-disabled")
        if not enabled:
            return False
        if eng is None:
            from torchmetrics_tpu.engine.compiled import CompiledUpdate

            eng = self._engine = CompiledUpdate(self)
        if queueing:
            # multi-step scan dispatch (engine/scan.py): queue this payload —
            # K steps fold into one donated lax.scan executable. forward()'s
            # inner updates (mutation depth > 1) bypass the queue: forward IS
            # a value request, so its batch must apply immediately. The async
            # tier (engine/async_dispatch.py) resolves HERE — only where a
            # scan queue is active — so an invalid TORCHMETRICS_TPU_ASYNC can
            # never raise on configurations that never read it
            from torchmetrics_tpu.engine.async_dispatch import resolve_async

            return eng.scan_step(args, kwargs, k, resolve_async(self.async_dispatch))
        return eng.step(args, kwargs)

    def _scan_depth(self) -> Optional[int]:
        """The active scan queue depth for THIS metric, or None (unqueued)."""
        if self.scan_steps is not None:
            return self.scan_steps or None  # 0 = forced off for this metric
        from torchmetrics_tpu.engine.scan import scan_k

        return scan_k()

    def _drain_scan(self, reason: str) -> int:
        """Flush any scan queue holding this metric's pending steps.

        Every state observation routes through here FIRST (the staleness
        contract of ``engine/scan.py``): a reader can never see state that is
        up to K steps behind the enqueued stream. A compute-group VIEW member
        observes its OWNER's state, so the owner's queue (stamped as
        ``_scan_peer`` at view materialization) drains too.
        """
        from torchmetrics_tpu.engine.scan import flush_metric

        drained = flush_metric(self, reason)
        peer_ref = self.__dict__.get("_scan_peer")
        if peer_ref is not None:
            peer = peer_ref()
            if peer is not None:
                drained += flush_metric(peer, reason)
        return drained

    def _epoch_enabled(self) -> bool:
        """Shared engine-enablement resolution (per-metric kwarg > overrides > auto)."""
        if self.compiled_update is False:
            return False
        if self.compiled_update is None:
            from torchmetrics_tpu.engine.config import engine_enabled

            return engine_enabled()
        return True

    def _epoch_engine(self):
        """Lazy per-instance epoch engine (engine/epoch.py)."""
        if self._epoch is None:
            from torchmetrics_tpu.engine.epoch import EpochEngine

            self._epoch = EpochEngine(self)
        return self._epoch

    def _packed_sync_allowed(self) -> bool:
        """Whether sync may ride the packed single-collective plan."""
        if not self._epoch_enabled():
            return False
        if self.compute_on_cpu:
            # list states live on host by request; the packed buffers would
            # drag them back through the device — eager path, counted
            self._epoch_engine().stats.fallback("sync:compute-on-cpu")
            return False
        return True

    def _epoch_sync_for_compute(self) -> Optional[tuple]:
        """The fused sync→reduce-fold→compute chain for this compute() call.

        Returns ``None`` when ineligible (the caller runs the classic
        sync_context path, whose ``sync`` may still ride the packed plan), or a
        1-tuple ``(value,)`` after the packed exchange has run and the synced
        states are written — ``value`` is ``engine.epoch.NO_VALUE`` when only
        the sync half fused (compute runs eagerly on the synced states).
        """
        if self._is_synced or not self._to_sync:
            return None
        if self.dist_sync_fn is not None or self.compute_on_cpu:
            return None
        da = self.distributed_available_fn
        if not (callable(da) and da()):
            return None
        if not self._epoch_enabled():
            return None
        eng = self._epoch_engine()
        snapshot = self._copy_state_refs()
        res = eng.sync_and_compute(process_group=self.process_group)
        if res is None:
            return None
        self._cache = snapshot
        self._is_synced = True
        return res

    def _engine_compute(self, compute: Callable, args: tuple, kwargs: Dict[str, Any]) -> Any:
        """Dispatch compute through the cached executable when possible."""
        if not args and not kwargs and self._epoch_enabled():
            handled, value = self._epoch_engine().cached_compute()
            if handled:
                return value
        return compute(*args, **kwargs)

    def _move_list_states_to_cpu(self) -> None:
        """Move list states to host memory to free HBM (reference ``metric.py:442-447``)."""
        cpu = jax.devices("cpu")[0]  # the host platform is always registered
        for key in self._defaults:
            current_val = getattr(self, key)
            if isinstance(current_val, Sequence) and not _is_array(current_val):
                setattr(self, key, [jax.device_put(v, cpu) for v in current_val])

    def _wrap_compute(self, compute: Callable) -> Callable:
        self._raw_compute = compute  # unwrapped body — what the epoch engine traces

        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            # compute observes state: pending scan payloads fold in first (the
            # engine/scan.py staleness contract)
            self._drain_scan("observation:compute")
            if self._update_count == 0:
                rank_zero_warn(
                    f"The ``compute`` method of metric {self.__class__.__name__} was called before the ``update``"
                    " method which may lead to errors, as metric states have not yet been updated.",
                    UserWarning,
                )
            elif not getattr(self, "_in_batch_value", False):
                from torchmetrics_tpu.engine import txn as _txn

                if _txn.quarantine_enabled() and getattr(self, _txn.ATTR, None) is not None:
                    # compute IS the sanctioned epoch-end boundary: flush the
                    # quarantine counter into EngineStats/events here, and warn
                    # when every updated batch was quarantined — the states are
                    # still at their defaults, which would otherwise read as a
                    # silently-wrong epoch value
                    if _txn.read_quarantine(self)["count"] >= self._update_count:
                        rank_zero_warn(
                            f"Every batch seen by metric {self.__class__.__name__} failed quarantine"
                            " admission — ``compute`` is folding default (empty) state. Inspect"
                            " the input pipeline or run with TORCHMETRICS_TPU_QUARANTINE=error.",
                            UserWarning,
                        )
            if self._computed is not None:
                return self._computed

            if self.__dict__.get("_comp_residuals"):
                # epoch-boundary re-anchoring (engine/numerics.py): fold each
                # compensated (value, residual) pair into a clean anchor so
                # compute() sees the corrected total and error growth restarts
                # from zero — pure device ops, no host transfer
                from torchmetrics_tpu.engine import numerics as _numerics

                _numerics.reanchor(self)

            fused = None
            if not args and not kwargs:
                # fused epoch chain: packed exchange + one executable doing
                # unpack → dist_reduce_fx folds → compute in a single graph
                fused = self._epoch_sync_for_compute()
            if fused is not None:
                from torchmetrics_tpu.engine.epoch import NO_VALUE

                try:
                    value = fused[0]
                    if value is NO_VALUE:  # sync fused, compute runs on synced states
                        with jax.profiler.TraceAnnotation(f"{type(self).__name__}.compute"):
                            value = self._engine_compute(compute, args, kwargs)
                    value = _squeeze_if_scalar(value)
                finally:
                    if self._is_synced and self._should_unsync:
                        self.unsync()
            else:
                with self.sync_context(
                    dist_sync_fn=self.dist_sync_fn,
                    should_sync=self._to_sync,
                    should_unsync=self._should_unsync,
                ), jax.profiler.TraceAnnotation(f"{type(self).__name__}.compute"):
                    value = _squeeze_if_scalar(self._engine_compute(compute, args, kwargs))

            if self.compute_with_cache:
                self._computed = value
            return value

        return wrapped_func

    # ------------------------------------------------------------------ abstract

    def update(self, *_: Any, **__: Any) -> None:
        """Override to update state from a batch."""
        raise NotImplementedError

    def compute(self) -> Any:
        """Override to compute the final value from state."""
        raise NotImplementedError

    # ------------------------------------------------------------------ plot

    def plot(self, *_: Any, **__: Any) -> Any:
        """Override to plot the metric value."""
        raise NotImplementedError

    def _plot(self, val: Optional[Any] = None, ax: Optional[Any] = None) -> Any:
        """Single/multi value plot helper (reference ``metric.py:...`` + ``utilities/plot.py:61``)."""
        from torchmetrics_tpu.utilities.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        fig, ax = plot_single_or_multi_val(
            val,
            ax=ax,
            higher_is_better=self.higher_is_better,
            name=self.__class__.__name__,
            lower_bound=self.plot_lower_bound,
            upper_bound=self.plot_upper_bound,
            legend_name=self.plot_legend_name,
        )
        return fig, ax

    # ------------------------------------------------------------------ lifecycle

    def reset(self) -> None:
        """Reset all states to their defaults (reference ``metric.py:623-638``)."""
        from torchmetrics_tpu.engine.scan import discard_metric

        # pending scan payloads are DISCARDED, not drained: applying updates
        # the reset immediately wipes is byte-identical to skipping them
        discard_metric(self, "reset")
        self._update_count = 0
        self._forward_cache = None
        self._computed = None
        for attr, default in self._defaults.items():
            if _is_array(default):
                setattr(self, attr, default)  # immutable → safe to share
            else:
                setattr(self, attr, [])
        self._cache = None
        self._is_synced = False
        self._none_folded = set()
        if self.__dict__.get("_sentinel_flags") is not None:
            # the health sentinel is sticky across updates/syncs but a reset
            # starts a fresh accumulation — flags from the previous epoch
            # must not bleed into the next one
            self._sentinel_flags = jnp.zeros((), jnp.int32)
        if self.__dict__.get("_quarantined_count") is not None:
            # same rule for the quarantine counter: growth already surfaced by
            # a sanctioned read stays in EngineStats; the device count restarts
            self._quarantined_count = jnp.zeros_like(self._quarantined_count)
            self._quarantine_reported = 0
        if self.__dict__.get("_comp_residuals"):
            # a reset starts a fresh accumulation: the compensation residual
            # restarts at zero with the states (fresh buffers — donation-safe
            # against snapshots still holding the old arrays)
            self._comp_residuals = {
                k: jnp.zeros_like(v) for k, v in self._comp_residuals.items()
            }

    def state_footprint(self) -> Dict[str, Any]:
        """Live HBM bytes held by this metric's states (see ``diag/costs.py``)."""
        from torchmetrics_tpu.diag.costs import state_footprint

        return state_footprint(self)

    def snapshot_compute(self) -> Any:
        """Scrape-anytime ``compute()`` on a shielded state copy (``serve/``).

        Pause-free: the live state keeps updating (and donating) while the
        value computes on a donation-proof snapshot; caches, sync status and
        counters on the live metric are untouched. Rank-local by design —
        cross-rank totals belong to the epoch sync. See
        :func:`torchmetrics_tpu.serve.snapshot.snapshot_compute`.
        """
        from torchmetrics_tpu.serve.snapshot import snapshot_compute

        return snapshot_compute(self)

    def clone(self) -> "Metric":
        """Deep copy of the metric (reference ``metric.py:640-642``)."""
        return deepcopy(self)

    def __getstate__(self) -> Dict[str, Any]:
        """Drop wrapped bound methods + compiled executables for pickling (reference ``metric.py:644-648``)."""
        # a clone/pickle captures state: pending scan payloads fold in first,
        # or the copy would silently lag the enqueued stream by up to K steps
        self._drain_scan("observation:clone")
        # _scan_peer is a weakref (unpicklable) into the ORIGINAL collection's
        # owner — meaningless for a clone, which re-stamps at materialization
        drop = (
            "update", "compute", "_update_signature", "_raw_update", "_raw_compute",
            "_engine", "_epoch", "_txn_stats", "_scan_peer",
        )
        return {k: v for k, v in self.__dict__.items() if k not in drop}

    def __setstate__(self, state: Dict[str, Any]) -> None:
        """Re-wrap update/compute on unpickle (reference ``metric.py:650-655``)."""
        self.__dict__.update(state)
        self.__dict__.setdefault("_none_folded", set())
        self.__dict__.setdefault("compiled_update", None)
        self.__dict__.setdefault("scan_steps", None)
        self.__dict__.setdefault("async_dispatch", None)
        # pre-spec pickles: roles re-derive lazily (counted spec_fallbacks)
        self.__dict__.setdefault("_state_specs", {})
        self._engine = None  # executables are per-process/per-instance; rebuilt lazily
        self._epoch = None
        self._update_signature = inspect.signature(self.update)
        self.update = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute = self._wrap_compute(self.compute)  # type: ignore[method-assign]
        # pickles carry host-serialized (single-device) arrays: rule-carrying
        # states re-place onto the active mesh (no-op when sharding is off)
        self._apply_shard_rules()

    def __setattr__(self, name: str, value: Any) -> None:
        """Write-protect class-constant metadata (reference ``metric.py:657-668``)."""
        if name in (
            "higher_is_better",
            "is_differentiable",
            "full_state_update",
            "plot_lower_bound",
            "plot_upper_bound",
            "plot_legend_name",
        ):
            raise RuntimeError(f"Can't change const `{name}`.")
        object.__setattr__(self, name, value)

    # ------------------------------------------------------------------ device / dtype

    @property
    def device(self) -> Any:
        """Device of the metric states (reference ``metric.py:671-674``)."""
        return self._device

    @property
    def dtype(self) -> Any:
        return self._dtype

    def to(self, device: Any) -> "Metric":
        """Place all states on ``device`` (the reference's ``_apply`` move, ``metric.py:714-761``)."""
        # queued payloads were padded/bucketed against the OLD device's
        # signature: fold them in before the states move
        self._drain_scan("observation:device-move")
        self._device = device

        def _move(x: Any) -> Any:
            return jax.device_put(x, device) if _is_array(x) else x

        self._map_states(_move)
        return self

    def cpu(self) -> "Metric":
        return self.to(jax.devices("cpu")[0])

    def set_dtype(self, dst_type: Any) -> "Metric":
        """Cast float states to ``dst_type`` (reference ``metric.py:703-712``)."""
        self._dtype_convert = True
        self._dtype = dst_type

        def _cast(x: Any) -> Any:
            if _is_array(x) and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(dst_type)
            return x

        self._map_states(_cast, include_defaults=True)
        self._dtype_convert = False
        return self

    def float(self) -> "Metric":
        """No-op: accidental dtype casts are blocked; use ``set_dtype`` (reference ``metric.py:683-702``)."""
        return self

    def double(self) -> "Metric":
        """No-op — use ``set_dtype`` (reference ``metric.py:689-695``)."""
        return self

    def half(self) -> "Metric":
        """No-op — use ``set_dtype`` (reference ``metric.py:696-702``)."""
        return self

    def _map_states(self, fn: Callable, include_defaults: bool = False) -> None:
        for attr in self._defaults:
            val = getattr(self, attr)
            if isinstance(val, list):
                setattr(self, attr, [fn(v) for v in val])
            else:
                setattr(self, attr, fn(val))
            if include_defaults:
                d = self._defaults[attr]
                self._defaults[attr] = [fn(v) for v in d] if isinstance(d, list) else fn(d)
        if self._computed is not None:
            self._computed = apply_to_collection(self._computed, (jax.Array, jnp.ndarray), fn)

    # ------------------------------------------------------------------ persistence

    def persistent(self, mode: bool = False) -> None:
        """Toggle persistence of all states (reference ``metric.py:763-766``)."""
        for key in self._persistent:
            self._persistent[key] = mode

    _UPDATE_COUNT_KEY = "_update_count"

    def state_dict(self, destination: Optional[Dict] = None, prefix: str = "", keep_vars: bool = False) -> Dict[str, Any]:
        """Serialize persistent states to numpy (reference ``metric.py:768-797``).

        ``_update_count`` rides along so a resumed metric keeps the weighting that
        ``merge_state`` and running means depend on.
        """
        destination = {} if destination is None else destination
        # a checkpoint must hold every enqueued step (engine/scan.py contract)
        self._drain_scan("observation:state_dict")
        wrote_any = False
        residuals = self.__dict__.get("_comp_residuals") or {}
        for key in self._defaults:
            if not self._persistent[key]:
                continue
            current_val = getattr(self, key)
            if _is_array(current_val) and key in residuals:
                # snapshots persist the ANCHORED total (value ⊕ residual folded
                # on the fly, read-only): restore starts with a zero residual
                # and loses only the sub-ulp remainder — see engine/numerics.py
                from torchmetrics_tpu.engine import numerics as _numerics

                current_val = _numerics.anchored_value(current_val, residuals[key])
            if _is_array(current_val):
                destination[prefix + key] = np.asarray(current_val)
            elif isinstance(current_val, list):
                destination[prefix + key] = [np.asarray(v) for v in current_val]
            else:
                destination[prefix + key] = current_val
            wrote_any = True
        if wrote_any:
            destination[prefix + self._UPDATE_COUNT_KEY] = self._update_count
        return destination

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "") -> None:
        """Restore states saved by ``state_dict`` (reference ``metric.py:799-816``)."""
        restored_any = False
        for key in self._defaults:
            name = prefix + key
            if name in state_dict:
                val = state_dict[name]
                if isinstance(val, list):
                    setattr(self, key, [jnp.asarray(v) for v in val])
                else:
                    arr = jnp.asarray(val)
                    setattr(self, key, arr)
                    # checkpoints don't carry fold flags: recover a None-reduced
                    # state's stacked-shard marker from rank (documented fallback)
                    if self._reductions.get(key) is None and _is_array(self._defaults[key]):
                        if arr.ndim == self._defaults[key].ndim + 1:
                            self._none_folded.add(key)
                        else:
                            self._none_folded.discard(key)
                restored_any = True
        count_key = prefix + self._UPDATE_COUNT_KEY
        if count_key in state_dict:
            self._update_count = int(state_dict[count_key])
        elif restored_any:
            # legacy checkpoints without the count: mark as updated at least once
            self._update_count = max(self._update_count, 1)
        if restored_any:
            # state changed under the cache — a prior compute() value is stale now
            self._computed = None
            # checkpoints hold host arrays: re-place rule-carrying states onto
            # the active mesh so a restore keeps the sharded placement
            self._apply_shard_rules()
            if self.__dict__.get("_comp_residuals"):
                # checkpoints carry anchored totals (state_dict folded the
                # residual in): a stale residual surviving the restore would
                # double-count the error it once compensated
                self._comp_residuals = {
                    k: jnp.zeros_like(v) for k, v in self._comp_residuals.items()
                }

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Keep only kwargs that ``update`` accepts (reference ``metric.py:818-837``)."""
        _params = (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
        _sign_params = self._update_signature.parameters
        filtered_kwargs = {
            k: v for k, v in kwargs.items() if (k in _sign_params and _sign_params[k].kind not in _params)
        }
        exists_var_keyword = any(v.kind == inspect.Parameter.VAR_KEYWORD for v in _sign_params.values())
        if exists_var_keyword:
            filtered_kwargs = kwargs
        return filtered_kwargs

    def __hash__(self) -> int:
        """Hash from class + state identity (reference ``metric.py:839-850``)."""
        hash_vals: list = [self.__class__.__name__, id(self)]
        for key in self._defaults:
            val = getattr(self, key)
            if isinstance(val, list):
                hash_vals.extend(id(v) for v in val)
            else:
                hash_vals.append(id(val))
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}()"

    def type(self, dst_type: Any) -> "Metric":
        """No-op — use ``set_dtype`` (reference ``metric.py:676-681``)."""
        return self

    # ------------------------------------------------------------------ operators (reference ``metric.py:863-999``)

    def __add__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, self, other)

    def __radd__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.add, other, self)

    def __sub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, self, other)

    def __rsub__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.subtract, other, self)

    def __mul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, self, other)

    def __rmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.multiply, other, self)

    def __truediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, self, other)

    def __rtruediv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.true_divide, other, self)

    def __floordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, self, other)

    def __rfloordiv__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.floor_divide, other, self)

    def __mod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, self, other)

    def __rmod__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.mod, other, self)

    def __pow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, self, other)

    def __rpow__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.power, other, self)

    def __matmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, self, other)

    def __rmatmul__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.matmul, other, self)

    def __and__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, self, other)

    def __rand__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_and, other, self)

    def __or__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, self, other)

    def __ror__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_or, other, self)

    def __xor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, self, other)

    def __rxor__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_xor, other, self)

    def __eq__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.equal, self, other)

    def __ne__(self, other: Any) -> "CompositionalMetric":  # type: ignore[override]
        return CompositionalMetric(jnp.not_equal, self, other)

    def __lt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less, self, other)

    def __le__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.less_equal, self, other)

    def __gt__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater, self, other)

    def __ge__(self, other: Any) -> "CompositionalMetric":
        return CompositionalMetric(jnp.greater_equal, self, other)

    def __abs__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __neg__(self) -> "CompositionalMetric":
        return CompositionalMetric(_neg, self, None)

    def __pos__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.abs, self, None)

    def __inv__(self) -> "CompositionalMetric":
        return CompositionalMetric(jnp.bitwise_not, self, None)

    __invert__ = __inv__

    def __getitem__(self, idx: Any) -> "CompositionalMetric":
        return CompositionalMetric(lambda x: x[idx], self, None)

    def __getnewargs__(self) -> tuple:
        return tuple(self.__getstate__().get("_defaults", ()))

    __iter__ = None


def _safe_int_sum(a: Any, b: Any) -> Any:
    """Additive state fold that cannot silently wrap an integer accumulator.

    Under the x64 flag sub-64-bit integer states widen to int64/uint64 BEFORE
    the add (overflow-safe at int64; the widened dtype is the accumulator's
    from here on). Without x64 no wider device integer exists — the fold stays
    in-dtype and the ``overflow_suspect`` sentinel bit is the guard.
    """
    if (
        _is_array(a)
        and jnp.issubdtype(a.dtype, jnp.integer)
        and jax.config.jax_enable_x64
        and jnp.dtype(a.dtype).itemsize < 8
    ):
        wide = jnp.uint64 if jnp.issubdtype(a.dtype, jnp.unsignedinteger) else jnp.int64
        return jnp.asarray(a, wide) + jnp.asarray(b, wide)
    return a + b


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Lazy arithmetic DAG over metrics (reference ``metric.py:1014-1132``)."""

    def __init__(self, operator: Callable, metric_a: Union[Metric, float, int, Array, None], metric_b: Union[Metric, float, int, Array, None]) -> None:
        super().__init__()
        self.op = operator
        if isinstance(metric_a, (int, float)) or (metric_a is not None and _is_array(metric_a)):
            self.metric_a: Any = jnp.asarray(metric_a)
        else:
            self.metric_a = metric_a
        if isinstance(metric_b, (int, float)) or (metric_b is not None and _is_array(metric_b)):
            self.metric_b: Any = jnp.asarray(metric_b)
        else:
            self.metric_b = metric_b

    def _sync_dist(self, dist_sync_fn: Optional[Callable] = None, process_group: Optional[Any] = None) -> None:
        pass  # No syncing required here — underlying metrics sync themselves (ref ``metric.py:1043``)

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        # also some parsing for kwargs?
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
            return self._forward_cache
        if val_b is None:
            if isinstance(self.metric_b, Metric):
                self._forward_cache = None
                return self._forward_cache
            self._forward_cache = self.op(val_a)
            return self._forward_cache
        self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else self.op}(\n    {self.metric_a!r},\n    {self.metric_b!r}\n  )\n)"
        return self.__class__.__name__ + _op_metrics

    def _wrap_compute(self, compute: Callable) -> Callable:
        return compute
