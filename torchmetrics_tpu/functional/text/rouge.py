"""ROUGE score (reference ``functional/text/rouge.py``).

Host-side token work feeding per-key score lists; sentence splitting for ROUGE-Lsum
uses a regex splitter by default (the reference requires nltk's downloaded punkt
model, ``rouge.py:44-60``) and accepts a user tokenizer/normalizer like the reference.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1,
    "rouge2": 2,
    "rouge3": 3,
    "rouge4": 4,
    "rouge5": 5,
    "rouge6": 6,
    "rouge7": 7,
    "rouge8": 8,
    "rouge9": 9,
    "rougeL": "L",
    "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")

_SENTENCE_RE = re.compile(r"(?<=[.!?])\s+|\n")


def _split_sentence(x: str) -> Sequence[str]:
    """Regex sentence splitter (reference uses nltk punkt, ``rouge.py:63-72``)."""
    parts = [s.strip() for s in _SENTENCE_RE.split(x)]
    return [s for s in parts if s]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    """P/R/F from hit counts (reference ``rouge.py:75-92``).

    Host floats throughout: creating three device scalars per (pair, rouge-key) made
    200 WMT pairs cost ~33 s on the tunneled TPU (thousands of ~100 ms dispatches);
    n-gram scoring is host work — only aggregated results become arrays.
    """
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    fmeasure = 2 * precision * recall / (precision + recall)
    return {"precision": float(precision), "recall": float(recall), "fmeasure": float(fmeasure)}


def _lcs_table(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> np.ndarray:
    """LCS DP table via numpy rows (reference ``rouge.py:95-114``)."""
    m, n = len(pred_tokens), len(target_tokens)
    table = np.zeros((m + 1, n + 1), dtype=np.int64)
    for i in range(1, m + 1):
        prev_row = table[i - 1]
        cur = table[i]
        for j in range(1, n + 1):
            if pred_tokens[i - 1] == target_tokens[j - 1]:
                cur[j] = prev_row[j - 1] + 1
            else:
                cur[j] = max(prev_row[j], cur[j - 1])
    return table


def _lcs(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    """Length of the longest common subsequence.

    Rides the native C++ two-row DP when available (ROUGE-L only needs the
    length; the pure-Python table was ~2/3 of rouge-score wall time): tokens map
    to local int ids, the DP runs in ``native/match.cpp``.
    """
    if pred_tokens and target_tokens:
        from torchmetrics_tpu.native.rle_mask import lcs_len

        ids: dict = {}
        a = np.fromiter((ids.setdefault(t, len(ids)) for t in pred_tokens), np.int64, len(pred_tokens))
        b = np.fromiter((ids.setdefault(t, len(ids)) for t in target_tokens), np.int64, len(target_tokens))
        native = lcs_len(a, b)
        if native is not None:
            return native
    return int(_lcs_table(pred_tokens, target_tokens)[-1, -1])


def _backtracked_lcs(
    lcs_table: np.ndarray, row_tokens: Sequence[str], col_tokens: Sequence[str]
) -> Sequence[int]:
    """Backtrack the LCS table to row-token indices.

    Row orientation and tie-breaking match the official rouge_score
    ``_backtrack_norec`` so ROUGE-Lsum reproduces its hit sets exactly.
    """
    i = len(row_tokens)
    j = len(col_tokens)
    backtracked: List[int] = []
    while i > 0 and j > 0:
        if row_tokens[i - 1] == col_tokens[j - 1]:
            backtracked.insert(0, i - 1)
            i -= 1
            j -= 1
        elif lcs_table[i][j - 1] > lcs_table[i - 1][j]:
            j -= 1
        else:
            i -= 1
    return backtracked


def _union_lcs(pred_tokens_list: Sequence[Sequence[str]], target_tokens: Sequence[str]) -> Sequence[str]:
    """Union of LCS indices into the target over all pred sentences (reference ``rouge.py:142-160``).

    The per-pair table rows the target sentence (official rouge_score orientation).
    """
    token_ids: set = set()
    for pred_tokens in pred_tokens_list:
        table = _lcs_table(target_tokens, pred_tokens)
        token_ids.update(_backtracked_lcs(table, target_tokens, pred_tokens))
    return [target_tokens[i] for i in sorted(token_ids)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """Lowercase/strip non-alphanumeric + optional stem (reference ``rouge.py:163-195``)."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and len(x) > 0)]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    """ROUGE-N P/R/F (reference ``rouge.py:198-220``)."""

    def _create_ngrams(tokens: Sequence[str], n: int) -> Counter:
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    pred_ngrams, target_ngrams = _create_ngrams(pred, n_gram), _create_ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}

    hits = sum(min(pred_ngrams[w], target_ngrams[w]) for w in set(pred_ngrams))
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    """ROUGE-L P/R/F (reference ``rouge.py:223-235``)."""
    pred_len, target_len = len(pred), len(target)
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    lcs = _lcs(pred, target)
    return _compute_metrics(lcs, pred_len, target_len)


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, float]:
    """ROUGE-Lsum P/R/F via union-LCS (reference ``rouge.py:238-277``)."""
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}

    def _get_token_counts(sentences: Sequence[Sequence[str]]) -> Counter:
        ngrams: Counter = Counter()
        for sentence in sentences:
            ngrams.update(sentence)
        return ngrams

    pred_tokens_count = _get_token_counts(pred)
    target_tokens_count = _get_token_counts(target)

    hits = 0
    for tgt in target:
        lcs = _union_lcs(pred, tgt)
        for token in lcs:
            if pred_tokens_count[token] > 0 and target_tokens_count[token] > 0:
                hits += 1
                pred_tokens_count[token] -= 1
                target_tokens_count[token] -= 1

    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sample (best or averaged over references) scores (reference ``rouge.py:280-391``)."""
    results: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}

    for pred_raw, target_raw in zip(preds, target):
        result_inner: Dict[Union[int, str], Dict[str, float]] = {key: {} for key in rouge_keys_values}
        result_avg: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}
        list_results = []
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        pred_lsum = []
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer) for s in _split_sentence(pred_raw)
            ]

        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            target_lsum = []
            if "Lsum" in rouge_keys_values:
                target_lsum = [
                    _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                    for s in _split_sentence(target_raw_inner)
                ]

            for rouge_key in rouge_keys_values:
                if isinstance(rouge_key, int):
                    score = _rouge_n_score(pred, tgt, rouge_key)
                elif rouge_key == "L":
                    score = _rouge_l_score(pred, tgt)
                else:  # Lsum
                    score = _rouge_lsum_score(pred_lsum, target_lsum)
                result_inner[rouge_key] = score
                result_avg[rouge_key].append(score)
            list_results.append(result_inner.copy())

        if accumulate == "best":
            key_curr = rouge_keys_values[0]
            all_fmeasure = np.asarray([float(v[key_curr]["fmeasure"]) for v in list_results])
            highest_idx = int(all_fmeasure.argmax())
            for rouge_key in rouge_keys_values:
                results[rouge_key].append(list_results[highest_idx][rouge_key])
        else:  # avg
            for rouge_key in rouge_keys_values:
                scores = result_avg[rouge_key]
                avg = {
                    tp: float(np.mean([float(s[tp]) for s in scores]))
                    for tp in ("precision", "recall", "fmeasure")
                }
                results[rouge_key].append(avg)

    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Any]]) -> Dict[str, Array]:
    """Average per-sample scores (reference ``rouge.py:394-408``).

    List entries may be host floats (fresh per-pair scores) or 1-d arrays (per-update
    batches); a bare array is a synced state (``dim_zero_cat`` of all samples). Every
    branch returns the scalar mean.
    """
    output: Dict[str, Array] = {}
    for rouge_key, scores in sentence_results.items():
        if isinstance(scores, list):
            if not scores:
                output[rouge_key] = jnp.asarray(0.0)
                continue
            flat = np.concatenate([np.atleast_1d(np.asarray(s, dtype=np.float64)) for s in scores])
            output[rouge_key] = jnp.asarray(np.mean(flat), dtype=jnp.float32)
        else:
            # synced state: dim_zero_cat produced one array of per-sample scores
            output[rouge_key] = jnp.mean(jnp.atleast_1d(jnp.asarray(scores)))
    return output


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE (reference ``rouge.py:411-520``).

    Example:
        >>> from torchmetrics_tpu.functional.text import rouge_score
        >>> out = rouge_score("the cat sat on the mat", "a cat sat on the mat")
        >>> print(round(float(out["rouge1_fmeasure"]), 4))
        0.8333
    """
    stemmer = None
    if use_stemmer:
        try:
            from nltk.stem.porter import PorterStemmer
        except ImportError as err:
            raise ModuleNotFoundError(
                "Stemmer support requires `nltk` which is not installed; pass `use_stemmer=False`"
                " or supply pre-stemmed text via a custom `normalizer`."
            ) from err
        stemmer = PorterStemmer()

    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate, stemmer, normalizer, tokenizer
    )

    output: Dict[str, List[Array]] = {
        f"rouge{rouge_key}_{tp}": [] for rouge_key in rouge_keys_values for tp in ("fmeasure", "precision", "recall")
    }
    for rouge_key, metrics in sentence_results.items():
        for metric in metrics:
            for tp, value in metric.items():
                output[f"rouge{rouge_key}_{tp}"].append(value)

    return _rouge_score_compute(output)
