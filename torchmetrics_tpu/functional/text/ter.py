"""Translation Edit Rate (reference ``functional/text/ter.py``).

Host-side shift-search + edit-distance, mirroring tercom semantics. Differences from
the reference implementation: the Levenshtein DP here is exact (full matrix, numpy
rows) instead of beam-limited with a trie cache (``helper.py:64-343``) — the beam is a
speed approximation that can miss the true minimum; the shift heuristics,
candidate-ranking tuple and termination limits are kept identical so scores match
tercom. Only the summed edit/length counters land in device states.
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

# edit ops, single-char codes: n(othing) s(ubstitute) i(nsert) d(elete)
_OP_N, _OP_S, _OP_I, _OP_D = "n", "s", "i", "d"


class _TercomTokenizer:
    """Tercom normalizer (reference ``ter.py:57-185``)."""

    _ASIAN_PUNCTUATION = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCTUATION = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    @lru_cache(maxsize=2**16)  # noqa: B019
    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = self._remove_punct(sentence)
            if self.asian_support:
                sentence = self._remove_asian_punct(sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = f" {sentence} "
        rules = [
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ]
        for pattern, replacement in rules:
            sentence = re.sub(pattern, replacement, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r" \1 ", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)

    @staticmethod
    def _remove_punct(sentence: str) -> str:
        return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)

    @classmethod
    def _remove_asian_punct(cls, sentence: str) -> str:
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r"", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r"", sentence)


def _preprocess_sentence(sentence: str, tokenizer: _TercomTokenizer) -> str:
    """Tokenize one sentence (reference ``ter.py:188-198``)."""
    return tokenizer(sentence.rstrip())


def _edit_distance_with_trace(prediction_tokens: List[str], reference_tokens: List[str]) -> Tuple[int, str]:
    """Exact Levenshtein distance + operations trace, tercom op preference.

    Preference when costs tie: substitute/nothing, then delete, then insert
    (matching the reference's swapped-for-flip ordering, ``helper.py:151-162``).
    """
    p_len, r_len = len(prediction_tokens), len(reference_tokens)
    cost = np.zeros((p_len + 1, r_len + 1), dtype=np.int64)
    op = np.empty((p_len + 1, r_len + 1), dtype="<U1")
    cost[:, 0] = np.arange(p_len + 1)
    cost[0, :] = np.arange(r_len + 1)
    op[:, 0] = _OP_D
    op[0, :] = _OP_I
    op[0, 0] = ""
    for i in range(1, p_len + 1):
        for j in range(1, r_len + 1):
            if prediction_tokens[i - 1] == reference_tokens[j - 1]:
                sub_cost, sub_op = cost[i - 1, j - 1], _OP_N
            else:
                sub_cost, sub_op = cost[i - 1, j - 1] + 1, _OP_S
            best_cost, best_op = sub_cost, sub_op
            if cost[i - 1, j] + 1 < best_cost:
                best_cost, best_op = cost[i - 1, j] + 1, _OP_D
            if cost[i, j - 1] + 1 < best_cost:
                best_cost, best_op = cost[i, j - 1] + 1, _OP_I
            cost[i, j] = best_cost
            op[i, j] = best_op

    trace = []
    i, j = p_len, r_len
    while i > 0 or j > 0:
        operation = op[i, j]
        trace.append(operation)
        if operation in (_OP_N, _OP_S):
            i -= 1
            j -= 1
        elif operation == _OP_I:
            j -= 1
        else:  # delete
            i -= 1
    return int(cost[-1, -1]), "".join(reversed(trace))


def _flip_trace(trace: str) -> str:
    """Swap insertions/deletions: recipe for rewriting b→a (reference ``helper.py:347-364``)."""
    table = str.maketrans({_OP_I: _OP_D, _OP_D: _OP_I})
    return trace.translate(table)


def _trace_to_alignment(trace: str) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Alignment + per-position error flags from a trace (reference ``helper.py:367-404``)."""
    reference_position = hypothesis_position = -1
    reference_errors: List[int] = []
    hypothesis_errors: List[int] = []
    alignments: Dict[int, int] = {}
    for operation in trace:
        if operation == _OP_N:
            hypothesis_position += 1
            reference_position += 1
            alignments[reference_position] = hypothesis_position
            reference_errors.append(0)
            hypothesis_errors.append(0)
        elif operation == _OP_S:
            hypothesis_position += 1
            reference_position += 1
            alignments[reference_position] = hypothesis_position
            reference_errors.append(1)
            hypothesis_errors.append(1)
        elif operation == _OP_I:
            hypothesis_position += 1
            hypothesis_errors.append(1)
        elif operation == _OP_D:
            reference_position += 1
            alignments[reference_position] = hypothesis_position
            reference_errors.append(1)
        else:
            raise ValueError(f"Unknown operation {operation!r}")
    return alignments, reference_errors, hypothesis_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """Matching word sub-sequences at different positions (reference ``ter.py:201-236``)."""
    for pred_start in range(len(pred_words)):
        for target_start in range(len(target_words)):
            if abs(target_start - pred_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if (
                    pred_start + length > len(pred_words)
                    or target_start + length > len(target_words)
                    or pred_words[pred_start + length - 1] != target_words[target_start + length - 1]
                ):
                    break
                yield pred_start, target_start, length
                if len(pred_words) == pred_start + length or len(target_words) == target_start + length:
                    break


def _handle_corner_cases_during_shifting(
    alignments: Dict[int, int],
    pred_errors: List[int],
    target_errors: List[int],
    pred_start: int,
    target_start: int,
    length: int,
) -> bool:
    """Shift-pruning corner cases (reference ``ter.py:239-272``)."""
    if sum(pred_errors[pred_start : pred_start + length]) == 0:
        return True
    if sum(target_errors[target_start : target_start + length]) == 0:
        return True
    if pred_start <= alignments[target_start] < pred_start + length:
        return True
    return False


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move words[start:start+length] to position ``target`` (reference ``ter.py:275-305``)."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return (
        words[:start] + words[start + length : length + target] + words[start : start + length] + words[length + target :]
    )


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    reference_tokens: List[str],
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of best-shift search (reference ``ter.py:308-385``)."""
    edit_distance, inverted_trace = _edit_distance_with_trace(pred_words, reference_tokens)
    trace = _flip_trace(inverted_trace)
    alignments, target_errors, pred_errors = _trace_to_alignment(trace)

    best: Optional[Tuple[int, int, int, int, List[str]]] = None
    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        if _handle_corner_cases_during_shifting(
            alignments, pred_errors, target_errors, pred_start, target_start, length
        ):
            continue
        prev_idx = -1
        for offset in range(-1, length):
            if target_start + offset == -1:
                idx = 0
            elif target_start + offset in alignments:
                idx = alignments[target_start + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted_words = _perform_shift(pred_words, pred_start, length, idx)
            candidate = (
                edit_distance - _edit_distance_with_trace(shifted_words, reference_tokens)[0],
                length,
                -pred_start,
                -idx,
                shifted_words,
            )
            checked_candidates += 1
            if not best or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if not best:
        return 0, pred_words, checked_candidates
    best_score, _, _, _, shifted_words = best
    return best_score, shifted_words, checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> int:
    """Edits to match one hypothesis/reference pair, with shifts (reference ``ter.py:388-419``)."""
    if len(target_words) == 0:
        return 0

    num_shifts = 0
    checked_candidates = 0
    input_words = pred_words
    while True:
        delta, new_input_words, checked_candidates = _shift_words(
            input_words, target_words, target_words, checked_candidates
        )
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words

    edit_distance, _ = _edit_distance_with_trace(input_words, target_words)
    return num_shifts + edit_distance


def _compute_sentence_statistics(
    pred_words: List[str], target_words: List[List[str]]
) -> Tuple[float, float]:
    """Best edits over references + average reference length (reference ``ter.py:422-445``)."""
    tgt_lengths = 0.0
    best_num_edits = 2e16
    for tgt_words in target_words:
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    avg_tgt_len = tgt_lengths / len(target_words)
    return best_num_edits, avg_tgt_len


def _compute_ter_score_from_statistics(num_edits: Array, tgt_length: Array) -> Array:
    """TER = edits / avg ref length (reference ``ter.py:448-462``)."""
    score = jnp.where(
        (tgt_length > 0) & (num_edits > 0),
        num_edits / jnp.where(tgt_length > 0, tgt_length, 1.0),
        jnp.where((tgt_length == 0) & (num_edits > 0), 1.0, 0.0),
    )
    return score


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    tokenizer: _TercomTokenizer,
    total_num_edits: Array,
    total_tgt_length: Array,
    sentence_ter: Optional[List[Array]] = None,
) -> Tuple[Array, Array, Optional[List[Array]]]:
    """Fold one batch into the summed states (reference ``ter.py:465-505``)."""
    if isinstance(preds, str):
        preds = [preds]
    target_: Sequence[Sequence[str]] = [[t] if isinstance(t, str) else t for t in target]

    edits_add = 0.0
    length_add = 0.0
    for pred, tgt in zip(preds, target_):
        tgt_words_ = [_preprocess_sentence(_tgt, tokenizer).split() for _tgt in tgt]
        pred_words_ = _preprocess_sentence(pred, tokenizer).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        edits_add += num_edits
        length_add += tgt_length
        if sentence_ter is not None:
            sentence_ter.append(
                _compute_ter_score_from_statistics(jnp.asarray(float(num_edits)), jnp.asarray(tgt_length))
            )
    return total_num_edits + edits_add, total_tgt_length + length_add, sentence_ter


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    """Corpus TER (reference ``ter.py:508-518``)."""
    return _compute_ter_score_from_statistics(total_num_edits, total_tgt_length)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """TER (reference ``ter.py:521-586``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.ter import translation_edit_rate
        >>> print(round(float(translation_edit_rate(preds, target)), 4))
        0.2222
    """
    if not isinstance(normalize, bool):
        raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
    if not isinstance(no_punctuation, bool):
        raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
    if not isinstance(lowercase, bool):
        raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
    if not isinstance(asian_support, bool):
        raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")

    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    total_num_edits = jnp.asarray(0.0)
    total_tgt_length = jnp.asarray(0.0)
    sentence_ter: Optional[List[Array]] = [] if return_sentence_level_score else None
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(
        preds, target, tokenizer, total_num_edits, total_tgt_length, sentence_ter
    )
    ter = _ter_compute(total_num_edits, total_tgt_length)
    if sentence_ter is not None:
        return ter, jnp.stack(sentence_ter)
    return ter
