"""InfoLM (reference ``functional/text/infolm.py``).

All nine information measures are implemented as pure jnp functions over masked-LM
token distributions. The masked LM comes from either path: ``model_name_or_path``
builds the full HF pipeline (tokenize → masked-LM logits → temperature softmax →
idf-weighted aggregation; Flax-first via ``utilities.hf`` with offline-clean errors),
or inject a callable ``model(sentences) -> (N, V) distributions`` directly.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

_ALLOWED_INFORMATION_MEASURE = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)

_EPS = 1e-12


class _InformationMeasure:
    """Dispatcher over the nine measures (reference ``infolm.py:57-231``)."""

    def __init__(
        self,
        information_measure: str = "kl_divergence",
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
    ) -> None:
        if information_measure not in _ALLOWED_INFORMATION_MEASURE:
            raise ValueError(
                f"Argument `information_measure` expected to be one of {_ALLOWED_INFORMATION_MEASURE}"
                f" but got {information_measure}."
            )
        if information_measure in ("alpha_divergence", "ab_divergence", "renyi_divergence") and not isinstance(
            alpha, float
        ):
            raise ValueError(f"Argument `alpha` is expected to be defined for {information_measure}.")
        if information_measure in ("beta_divergence", "ab_divergence") and not isinstance(beta, float):
            raise ValueError(f"Argument `beta` is expected to be defined for {information_measure}.")
        if information_measure == "alpha_divergence" and alpha in (0.0, 1.0):
            raise ValueError(f"Parameter `alpha` is expected to be differened from 0 and 1 for {information_measure}.")
        if information_measure == "beta_divergence" and beta in (0.0, -1.0):
            raise ValueError(f"Parameter `beta` is expected to be differened from 0 and -1 for {information_measure}.")
        if information_measure == "ab_divergence" and any(p in (0.0,) for p in (alpha, beta)) or (
            information_measure == "ab_divergence" and alpha is not None and beta is not None and alpha + beta == 0
        ):
            raise ValueError(
                f"Parameters `alpha`, `beta` and their sum are expected to differ from 0 for {information_measure}."
            )
        self.information_measure = information_measure
        self.alpha = alpha
        self.beta = beta

    def __call__(self, preds_distribution: Array, target_distribution: Array) -> Array:
        return getattr(self, f"_calculate_{self.information_measure}")(preds_distribution, target_distribution)

    @staticmethod
    def _calculate_kl_divergence(p: Array, q: Array) -> Array:
        return jnp.sum(p * (jnp.log(p + _EPS) - jnp.log(q + _EPS)), axis=-1)

    def _calculate_alpha_divergence(self, p: Array, q: Array) -> Array:
        a = self.alpha
        return (1.0 / (a * (a - 1))) * (jnp.sum(q**a * p ** (1 - a), axis=-1) - 1)

    def _calculate_beta_divergence(self, p: Array, q: Array) -> Array:
        b = self.beta
        term1 = 1.0 / (b * (b + 1)) * jnp.sum(p ** (b + 1), axis=-1)
        term2 = 1.0 / b * jnp.sum(q * p**b, axis=-1)
        term3 = 1.0 / (b + 1) * jnp.sum(q ** (b + 1), axis=-1)
        return term1 - term2 + term3

    def _calculate_ab_divergence(self, p: Array, q: Array) -> Array:
        a, b = self.alpha, self.beta
        term1 = 1.0 / (b * (a + b)) * jnp.sum(q ** (a + b), axis=-1)
        term2 = 1.0 / (a * b) * jnp.sum(q**a * p**b, axis=-1)
        term3 = 1.0 / (a * (a + b)) * jnp.sum(p ** (a + b), axis=-1)
        return term1 - term2 + term3

    def _calculate_renyi_divergence(self, p: Array, q: Array) -> Array:
        a = self.alpha
        return jnp.log(jnp.sum(q**a * p ** (1 - a), axis=-1)) / (a - 1)

    @staticmethod
    def _calculate_l1_distance(p: Array, q: Array) -> Array:
        return jnp.sum(jnp.abs(p - q), axis=-1)

    @staticmethod
    def _calculate_l2_distance(p: Array, q: Array) -> Array:
        return jnp.sqrt(jnp.sum((p - q) ** 2, axis=-1))

    @staticmethod
    def _calculate_l_infinity_distance(p: Array, q: Array) -> Array:
        return jnp.max(jnp.abs(p - q), axis=-1)

    @staticmethod
    def _calculate_fisher_rao_distance(p: Array, q: Array) -> Array:
        return 2 * jnp.arccos(jnp.clip(jnp.sum(jnp.sqrt(p * q), axis=-1), 0.0, 1.0))


def make_hf_masked_lm_distribution_fn(
    model_name_or_path: str,
    temperature: float = 0.25,
    idf: bool = True,
    max_length: int = 512,
) -> Callable[[List[str]], Array]:
    """Build the reference's masked-LM sentence-distribution pipeline from a HF id.

    Per the reference (``functional/text/infolm.py:354-403``): for every sequence
    position, replace that token with ``[MASK]``, run the masked LM, and take the
    temperature-softmaxed *predictive* distribution at the masked position; aggregate
    the per-position distributions into one (V,) sentence distribution, weighting by
    idf of the replaced token (or uniformly), with special tokens (PAD/SEP/CLS)
    excluded from the aggregation.
    """
    import numpy as np

    from torchmetrics_tpu.utilities.hf import (
        hf_logits_forward,
        hf_tokenize,
        load_hf_model_and_tokenizer,
        model_max_length,
    )

    hf_model, tokenizer = load_hf_model_and_tokenizer(model_name_or_path, "FlaxAutoModelForMaskedLM")
    max_length = model_max_length(hf_model, max_length)

    token_fn = make_hf_masked_lm_distribution_from_tokens_fn(model_name_or_path, temperature, idf)

    def fn(sentences: List[str]) -> Array:
        ids, attn = hf_tokenize(tokenizer, sentences, max_length=max_length, padding="longest")
        return token_fn(ids, attn)

    return fn


def make_hf_masked_lm_distribution_fns(
    model_name_or_path: str,
    temperature: float = 0.25,
    idf: bool = True,
    max_length: int = 512,
) -> Tuple[Callable[[List[str]], Tuple[Array, Array]], Callable[[Array, Array], Array], int]:
    """(tokenize_fn, distribution_from_tokens_fn, pad_width) — the split pipeline.

    The modular metric tokenizes at ``update`` (fixed ``pad_width`` so token arrays
    ride the cross-process gather as cat states) and computes distributions at
    ``compute`` over the gathered corpus, which keeps idf corpus-wide. Padding width
    is score-neutral: pad tokens are special tokens, excluded from aggregation.
    """
    from torchmetrics_tpu.utilities.hf import (
        hf_tokenize,
        load_hf_model_and_tokenizer,
        model_max_length,
    )

    hf_model, tokenizer = load_hf_model_and_tokenizer(model_name_or_path, "FlaxAutoModelForMaskedLM")
    pad_width = model_max_length(hf_model, max_length)

    def tokenize_fn(sentences: List[str]) -> Tuple[Array, Array]:
        return hf_tokenize(tokenizer, sentences, max_length=pad_width, padding="max_length")

    token_fn = make_hf_masked_lm_distribution_from_tokens_fn(model_name_or_path, temperature, idf)
    return tokenize_fn, token_fn, pad_width


def make_hf_masked_lm_distribution_from_tokens_fn(
    model_name_or_path: str,
    temperature: float = 0.25,
    idf: bool = True,
) -> Callable[[Array, Array], Array]:
    """``(input_ids, attention_mask) -> (N, V)`` sentence distributions."""
    import numpy as np

    from torchmetrics_tpu.utilities.hf import hf_logits_forward, load_hf_model_and_tokenizer

    hf_model, tokenizer = load_hf_model_and_tokenizer(model_name_or_path, "FlaxAutoModelForMaskedLM")
    forward = hf_logits_forward(hf_model)
    mask_token_id = tokenizer.mask_token_id
    if mask_token_id is None:
        raise ValueError(
            f"Tokenizer for `{model_name_or_path!r}` has no mask token — InfoLM requires a masked LM."
        )
    special_ids = [i for i in (tokenizer.pad_token_id, tokenizer.sep_token_id, tokenizer.cls_token_id) if i is not None]

    def fn(ids: Array, attn: Array) -> Array:
        ids_np = np.asarray(ids)
        attn_np = np.asarray(attn)
        # trim trailing all-pad columns: the metric path pads to model_max_length for
        # fixed-width gatherable states, but every forward is O(L^2) attention — and
        # padding is score-neutral (pad positions are excluded from aggregation), so
        # run the model at the corpus's true longest length
        content_cols = np.flatnonzero(attn_np.any(axis=0))
        if content_cols.size and content_cols[-1] + 1 < ids_np.shape[1]:
            keep = int(content_cols[-1]) + 1
            ids_np = ids_np[:, :keep]
            attn_np = attn_np[:, :keep]
        attn = jnp.asarray(attn_np)
        seq_len = ids_np.shape[1]
        # 1s on real content tokens (reference ``_get_token_mask:330-352``)
        token_mask = ~np.isin(ids_np, special_ids)
        if idf:
            from torchmetrics_tpu.functional.text.bert import _compute_idf, _idf_weights

            # token_mask (not the attention mask) as the weight mask: special tokens
            # are excluded from the aggregation (reference ``infolm.py:398-401``)
            pos_w = np.asarray(
                _idf_weights(ids_np, token_mask, _compute_idf([jnp.asarray(ids_np)], [attn])),
                dtype=np.float64,
            )
        else:
            pos_w = token_mask.astype(np.float64)

        acc = None
        for pos in range(seq_len):
            if not token_mask[:, pos].any():
                continue
            masked = ids_np.copy()
            masked[:, pos] = mask_token_id
            logits = forward(jnp.asarray(masked), jnp.asarray(attn))  # (N, L, V)
            probs = np.asarray(jax.nn.softmax(logits[:, pos, :] / temperature, axis=-1), dtype=np.float64)
            contrib = probs * pos_w[:, pos : pos + 1]
            acc = contrib if acc is None else acc + contrib
        if acc is None:
            raise ValueError("No content tokens found in the input sentences.")
        acc /= np.clip(pos_w.sum(axis=1, keepdims=True), _EPS, None)
        return jnp.asarray(acc)

    return fn


def infolm(
    preds: Union[str, List[str]],
    target: Union[str, List[str]],
    model_name_or_path: Optional[str] = None,
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    model: Optional[Callable] = None,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """InfoLM with an injected masked-LM (reference ``infolm.py:234-375``).

    ``model`` must be a callable ``(sentences: List[str]) -> (N, V) distributions``
    over the vocabulary (already temperature-scaled and idf-aggregated if desired).
    """
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("Number of predicted and reference sentences must be the same!")
    if model is None and model_name_or_path is not None:
        model = make_hf_masked_lm_distribution_fn(model_name_or_path, temperature=temperature, idf=idf)
    if model is None or isinstance(model, str) or not callable(model):
        raise ValueError(
            "Either pass `model_name_or_path` (a cached/local HF masked-LM) or a callable"
            " `model(sentences) -> (N, V) distributions`."
        )
    measure = _InformationMeasure(information_measure, alpha, beta)
    preds_distribution = model(preds)
    target_distribution = model(target)
    scores = measure(preds_distribution, target_distribution)
    if return_sentence_level_score:
        return scores.mean(), scores
    return scores.mean()
