"""SacreBLEU (reference ``functional/text/sacre_bleu.py``).

Same four tensor states as BLEU; adds the sacrebleu tokenizer family. The ``intl``
tokenizer is implemented with ``unicodedata`` character categories instead of the
optional third-party ``regex`` module the reference requires, so it is always
available. ``ja-mecab``-style tokenizers need external C libraries and are not
supported.
"""

from __future__ import annotations

import re
import unicodedata
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update

Array = jax.Array

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")

# Main CJK codepoint ranges (reference ``sacre_bleu.py:52-77``)
_UCODE_RANGES = (
    ("\u3400", "\u4db5"),  # CJK Unified Ideographs Extension A
    ("\u4e00", "\u9fa5"),  # CJK Unified Ideographs
    ("\u9fa6", "\u9fbb"),  # CJK Unified Ideographs, release 4.1
    ("\uf900", "\ufa2d"),  # CJK Compatibility Ideographs
    ("\ufa30", "\ufa6a"),  # CJK Compatibility Ideographs, release 3.2
    ("\ufa70", "\ufad9"),  # CJK Compatibility Ideographs, release 4.1
    ("\U00020000", "\U0002a6d6"),  # CJK Unified Ideographs Extension B
    ("\U0002f800", "\U0002fa1d"),  # CJK Compatibility Supplement
    ("\uff00", "\uffef"),  # Full-width ASCII + half-width forms
    ("\u2e80", "\u2eff"),  # CJK Radicals Supplement
    ("\u3000", "\u303f"),  # CJK punctuation marks
    ("\u31c0", "\u31ef"),  # CJK strokes
    ("\u2f00", "\u2fdf"),  # Kangxi Radicals
    ("\u2ff0", "\u2fff"),  # Ideographic Description Characters
    ("\u3100", "\u312f"),  # Bopomofo
    ("\u31a0", "\u31bf"),  # Bopomofo Extended
    ("\ufe10", "\ufe1f"),  # Vertical forms
    ("\ufe30", "\ufe4f"),  # CJK Compatibility Forms
    ("\u2600", "\u26ff"),  # Miscellaneous symbols
    ("\u2700", "\u27bf"),  # Dingbats
    ("\u3200", "\u32ff"),  # Enclosed CJK letters and months
    ("\u3300", "\u33ff"),  # CJK compatibility
)


class _SacreBLEUTokenizer:
    """Sacrebleu tokenizer family (reference ``sacre_bleu.py:80-273``)."""

    _REGEX = (
        (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
        (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
        (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
        (re.compile(r"([0-9])(-)"), r"\1 \2 "),
    )

    _TOKENIZE_FN = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
    }

    def __init__(self, tokenize: str = "13a", lowercase: bool = False) -> None:
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized_line = self.tokenize_fn(line)
        return self._lower(tokenized_line, self.lowercase).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        tokenize_fn = getattr(cls, cls._TOKENIZE_FN[tokenize])
        return cls._lower(tokenize_fn(line), lowercase).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for _re, repl in cls._REGEX:
            line = _re.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "")
        line = line.replace("-\n", "")
        line = line.replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"')
            line = line.replace("&amp;", "&")
            line = line.replace("&lt;", "<")
            line = line.replace("&gt;", ">")
        return cls._tokenize_regex(f" {line} ")

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        line_in_chars = ""
        for char in line:
            if cls._is_chinese_char(char):
                line_in_chars += f" {char} "
            else:
                line_in_chars += char
        return cls._tokenize_regex(line_in_chars)

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        """mteval-v14 international tokenization via unicodedata categories.

        Punctuation (P*) is split off unless between digits; symbols (S*) always split.
        """
        out = []
        n = len(line)
        for i, ch in enumerate(line):
            cat = unicodedata.category(ch)
            if cat.startswith("P"):
                # (\P{N})(\p{P}) / (\p{P})(\P{N}): each rule needs an actual neighboring
                # non-digit character — at string boundaries neither matches, so
                # digit-adjacent punctuation stays attached ("1976." → one token).
                prev_is_nondigit = i > 0 and not unicodedata.category(line[i - 1]).startswith("N")
                next_is_nondigit = i + 1 < n and not unicodedata.category(line[i + 1]).startswith("N")
                if prev_is_nondigit or next_is_nondigit:
                    out.append(f" {ch} ")
                else:
                    out.append(ch)
            elif cat.startswith("S"):
                out.append(f" {ch} ")
            else:
                out.append(ch)
        return " ".join("".join(out).split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(char for char in line)

    @staticmethod
    def _lower(line: str, lowercase: bool) -> str:
        return line.lower() if lowercase else line


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """SacreBLEU (reference ``sacre_bleu.py:276-342``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.sacre_bleu import sacre_bleu_score
        >>> print(round(float(sacre_bleu_score(preds, target)), 4))
        0.0
    """
    if tokenize not in AVAILABLE_TOKENIZERS:
        raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    preds_len = jnp.asarray(0.0)
    target_len = jnp.asarray(0.0)
    tokenize_fn = _SacreBLEUTokenizer(tokenize, lowercase)
    numerator, denominator, preds_len, target_len = _bleu_score_update(
        preds, target, numerator, denominator, preds_len, target_len, n_gram, tokenize_fn
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
