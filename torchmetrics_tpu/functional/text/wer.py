"""Word error rate (reference ``functional/text/wer.py``)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Σ edit ops + Σ reference words (reference ``wer.py:23-48``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = pred.split()
        tgt_tokens = tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += len(tgt_tokens)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _wer_compute(errors: Array, total: Array) -> Array:
    """Reference ``wer.py:51-61``."""
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WER (reference ``wer.py:64-88``).

    Example:
        >>> from torchmetrics_tpu.functional import word_error_rate
        >>> preds = ['this is the prediction', 'there is an other sample']
        >>> target = ['this is the reference', 'there is another one']
        >>> print(float(word_error_rate(preds, target)))
        0.5
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)
