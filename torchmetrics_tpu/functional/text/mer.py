"""Match error rate (reference ``functional/text/mer.py``)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Σ edit ops + Σ max(len_ref, len_pred) (reference ``mer.py:23-50``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = pred.split()
        tgt_tokens = tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += max(len(tgt_tokens), len(pred_tokens))
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _mer_compute(errors: Array, total: Array) -> Array:
    """Reference ``mer.py:53-63``."""
    return errors / total


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """MER (reference ``mer.py:66-90``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.mer import match_error_rate
        >>> print(round(float(match_error_rate(preds, target)), 4))
        0.2222
    """
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)
