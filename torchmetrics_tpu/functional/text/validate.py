"""Corpus-format validation shared by text metrics (reference ``functional/text/helper.py:293-343``)."""

from __future__ import annotations

from typing import Sequence, Tuple, Union


def _validate_inputs(
    ref_corpus: Union[Sequence[str], Sequence[Sequence[str]]],
    hypothesis_corpus: Union[str, Sequence[str]],
) -> Tuple[Sequence[Sequence[str]], Sequence[str]]:
    """Normalize hypothesis/reference corpora shapes (reference ``helper.py:293-343``)."""
    if isinstance(hypothesis_corpus, str):
        hypothesis_corpus = [hypothesis_corpus]

    if all(isinstance(ref, str) for ref in ref_corpus):
        ref_corpus = [ref_corpus] if len(hypothesis_corpus) == 1 else [[ref] for ref in ref_corpus]

    if hypothesis_corpus and all(ref for ref in ref_corpus) and len(ref_corpus) != len(hypothesis_corpus):
        raise ValueError(f"Corpus has different size {len(ref_corpus)} != {len(hypothesis_corpus)}")
    return ref_corpus, hypothesis_corpus
