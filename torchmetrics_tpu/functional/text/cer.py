"""Character error rate (reference ``functional/text/cer.py``)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Σ char edit ops + Σ reference chars (reference ``cer.py:22-48``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    errors = 0
    total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = list(pred)
        tgt_tokens = list(tgt)
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += len(tgt_tokens)
    return jnp.asarray(float(errors)), jnp.asarray(float(total))


def _cer_compute(errors: Array, total: Array) -> Array:
    """Reference ``cer.py:51-61``."""
    return errors / total


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """CER (reference ``cer.py:64-87``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.cer import char_error_rate
        >>> print(round(float(char_error_rate(preds, target)), 4))
        0.2432
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)
