"""Perplexity (reference ``functional/text/perplexity.py``).

Fully tensor-native — the one text metric whose hot path belongs on the TPU. Uses the
identity ``-log p(target) = logsumexp(logits) - logits[target]`` (numerically stable,
one vocab-axis reduction, no materialized (N, V) log-probability table) where the
reference materializes the full softmax then indexes a diagonal
(``perplexity.py:75-84``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_FLOAT_OR_DOUBLE = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def _check_shape_and_type_consistency(preds: Array, target: Array) -> None:
    """Validate [B, T, V] logits vs [B, T] targets (reference ``perplexity.py:21-64``)."""
    if preds.ndim != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {preds.ndim}."
        )
    if target.ndim != 2:
        raise ValueError(
            f"Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len], but got {target.ndim}."
        )
    if preds.shape[:2] != target.shape:
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {preds.shape[:2]} and {target.shape}."
        )
    if not any(preds.dtype == d for d in _FLOAT_OR_DOUBLE):
        raise TypeError(
            f"Input tensor `preds` is expected to be of floating point type but got {preds.dtype}."
        )
    if not jnp.issubdtype(target.dtype, jnp.integer):
        raise TypeError(f"Input tensor `target` is expected to be of integer type but got {target.dtype}.")


def _perplexity_update(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Tuple[Array, Array]:
    """Σ −log p(target) + valid-token count (reference ``perplexity.py:67-96``)."""
    _check_shape_and_type_consistency(preds, target)

    logits = preds.reshape(-1, preds.shape[-1]).astype(jnp.float32)
    target = target.reshape(-1)

    if ignore_index is not None:
        mask = target != ignore_index
        target = jnp.where(mask, target, 0)
    else:
        mask = jnp.ones_like(target, dtype=bool)

    # -log p(target) = logsumexp(logits) - logits[target]: one reduction pass over the
    # vocab axis instead of materialising the full (N, V) log_softmax (halves HBM
    # traffic on LM-eval shapes — the vocab table is the whole cost here)
    lse = jax.scipy.special.logsumexp(logits, axis=1)
    picked = jnp.take_along_axis(logits, target[:, None], axis=1).squeeze(1)
    total_log_probs = jnp.sum((lse - picked) * mask)
    count = mask.sum()
    return total_log_probs, count


def _perplexity_compute(total: Array, count: Array) -> Array:
    """exp of mean negative log likelihood (reference ``perplexity.py:99-108``)."""
    return jnp.exp(total / count)


def perplexity(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Array:
    """Perplexity (reference ``perplexity.py:111-140``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.text import perplexity
        >>> logits = jnp.log(jnp.asarray([[[0.5, 0.25, 0.25], [0.25, 0.5, 0.25]]]))
        >>> print(round(float(perplexity(logits, jnp.asarray([[0, 1]]))), 2))
        2.0
    """
    total, count = _perplexity_update(preds, target, ignore_index)
    return _perplexity_compute(total, count)
