"""Extended Edit Distance (reference ``functional/text/eed.py``).

Host-side character DP (CDER-style with jump + coverage costs). The per-row
recurrence is vectorized with numpy: the deletion chain
``next[i] = min(next[i−1]+del, …)`` is a min-plus prefix scan,
``min.accumulate(m − i·del) + i·del``, so rows cost O(n) numpy ops instead of the
reference's per-cell Python loop (``eed.py:25-77``).
"""

from __future__ import annotations

import re
import unicodedata
from math import inf
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.text.validate import _validate_inputs

Array = jax.Array


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Extended edit distance for one sentence pair (reference ``eed.py:25-77``)."""
    n = len(hyp)
    number_of_visits = np.full(n + 1, -1, dtype=np.int64)
    row = np.ones(n + 1)
    row[0] = 0.0  # CDER initialisation
    hyp_chars = np.asarray([ord(c) for c in hyp], dtype=np.int64) if n else np.zeros(0, dtype=np.int64)
    i_del = np.arange(n + 1) * deletion

    for w in range(1, len(ref) + 1):
        dist = (hyp_chars != ord(ref[w - 1])).astype(np.float64) if n else np.zeros(0)
        m = np.empty(n + 1)
        m[0] = row[0] + 1.0
        if n:
            np.minimum(row[:-1] + dist, row[1:] + insertion, out=m[1:])
        # deletion chain: next[i] = min_{k<=i} m[k] + (i-k)*deletion
        next_row = np.minimum.accumulate(m - i_del) + i_del

        min_index = int(next_row.argmin())
        number_of_visits[min_index] += 1

        if ref[w - 1] == " ":
            jump = alpha + next_row[min_index]
            next_row = np.minimum(next_row, jump)
        row = next_row

    coverage = rho * np.where(number_of_visits >= 0, number_of_visits, 1).sum()
    return min(1.0, (row[-1] + coverage) / (float(len(ref)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English preprocessing (reference ``eed.py:80-118``)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, replacement in ((".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")):
        sentence = sentence.replace(pattern, replacement)
    rules_re = [
        (r"\s+", r" "),
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),
    ]
    for pattern, replacement in rules_re:
        sentence = re.sub(pattern, replacement, sentence)
    for pattern, replacement in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(pattern, replacement)
    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    """Japanese preprocessing (reference ``eed.py:121-133``)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_compute(sentence_level_scores: List[Array]) -> Array:
    """Mean of sentence scores (reference ``eed.py:136-146``)."""
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0)
    return jnp.stack(sentence_level_scores).sum() / len(sentence_level_scores)


def _preprocess_sentences(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str,
) -> Tuple[Sequence[str], Sequence[Sequence[str]]]:
    """Validate + language preprocessing (reference ``eed.py:149-183``)."""
    target, preds = _validate_inputs(hypothesis_corpus=preds, ref_corpus=target)
    if language == "en":
        preprocess_function = _preprocess_en
    elif language == "ja":
        preprocess_function = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
    preds = [preprocess_function(pred) for pred in preds]
    target = [[preprocess_function(ref) for ref in reference] for reference in target]
    return preds, target


def _compute_sentence_statistics(
    preds_word: str,
    target_words: Union[str, Sequence[str]],
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Array:
    """Best (lowest) score over references (reference ``eed.py:186-212``)."""
    best_score = inf
    for reference in target_words:
        score = _eed_function(preds_word, reference, alpha, rho, deletion, insertion)
        if score < best_score:
            best_score = score
    return jnp.asarray(best_score)


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
    sentence_eed: Optional[List[Array]] = None,
) -> List[Array]:
    """Append per-sentence scores (reference ``eed.py:215-252``)."""
    preds, target = _preprocess_sentences(preds, target, language)
    if sentence_eed is None:
        sentence_eed = []
    if 0 in (len(preds), len(target[0])):
        return sentence_eed
    for hypothesis, target_words in zip(preds, target):
        score = _compute_sentence_statistics(hypothesis, target_words, alpha, rho, deletion, insertion)
        sentence_eed.append(score)
    return sentence_eed


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """EED (reference ``eed.py:255-313``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.eed import extended_edit_distance
        >>> print(round(float(extended_edit_distance(preds, target)), 4))
        0.2456
    """
    for param_name, param in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
        if not isinstance(param, float) or param < 0:
            raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")

    sentence_level_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_level_scores)
    if return_sentence_level_score:
        return average, jnp.stack(sentence_level_scores)
    return average
