"""chrF / chrF++ (reference ``functional/text/chrf.py``).

The reference keeps per-order counter *dicts* as dynamically-named states
(``chrf.py:48-77``); here each statistic is one fixed-shape array indexed by n-gram
order — (n_char_order,) and (n_word_order,) sum states, two psums at sync.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_EPS_SMOOTHING = 1e-16
_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    """Character stream, optionally stripping whitespace (reference ``chrf.py:80-92``)."""
    if whitespace:
        return list(sentence)
    return list("".join(sentence.split()))


def _separate_word_and_punctuation(word: str) -> List[str]:
    """Split leading/trailing punctuation (reference ``chrf.py:95-114``)."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    """Tokenize into words with separated punctuation (reference ``chrf.py:117-126``)."""
    return sum((_separate_word_and_punctuation(word) for word in sentence.strip().split()), [])


def _ngram_counts(char_or_word_list: List[str], n_gram_order: int) -> Dict[int, Counter]:
    """Counters of n-grams per order 1..n (reference ``chrf.py:129-143``)."""
    ngrams: Dict[int, Counter] = {}
    for n in range(1, n_gram_order + 1):
        ngrams[n] = Counter(
            tuple(char_or_word_list[i : i + n]) for i in range(len(char_or_word_list) - n + 1)
        )
    return ngrams


def _sentence_statistics(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[Dict[int, Counter], Dict[int, Counter], np.ndarray, np.ndarray]:
    """Char/word n-gram counts + per-order totals (reference ``chrf.py:146-193``)."""
    if lowercase:
        sentence = sentence.lower()
    char_n_grams = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_n_grams = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    char_totals = np.asarray([sum(char_n_grams[n].values()) for n in range(1, n_char_order + 1)], dtype=np.float64)
    word_totals = np.asarray([sum(word_n_grams[n].values()) for n in range(1, n_word_order + 1)], dtype=np.float64)
    return char_n_grams, word_n_grams, char_totals, word_totals


def _matches(hyp: Dict[int, Counter], ref: Dict[int, Counter]) -> np.ndarray:
    """Per-order clipped match counts (reference ``chrf.py:196-217``)."""
    return np.asarray(
        [sum((hyp[n] & ref[n]).values()) for n in sorted(hyp)], dtype=np.float64
    )


def _fscore_from_arrays(
    matching_char: np.ndarray,
    matching_word: np.ndarray,
    hyp_char: np.ndarray,
    hyp_word: np.ndarray,
    ref_char: np.ndarray,
    ref_word: np.ndarray,
    n_order: float,
    beta: float,
) -> float:
    """chrF score from per-order totals (reference ``chrf.py:235-288``)."""

    def _f(matching, hyp, ref):
        precision = np.where(hyp > 0, matching / np.where(hyp > 0, hyp, 1.0), 0.0)
        recall = np.where(ref > 0, matching / np.where(ref > 0, ref, 1.0), 0.0)
        denom = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
        return (1 + beta**2) * precision * recall / denom

    return float((_f(matching_char, hyp_char, ref_char).sum() + _f(matching_word, hyp_word, ref_word).sum()) / n_order)


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    total_preds_char_n_grams: Array,
    total_preds_word_n_grams: Array,
    total_target_char_n_grams: Array,
    total_target_word_n_grams: Array,
    total_matching_char_n_grams: Array,
    total_matching_word_n_grams: Array,
    n_char_order: int,
    n_word_order: int,
    n_order: float,
    beta: float,
    lowercase: bool,
    whitespace: bool,
    sentence_chrf_score: Optional[List[Array]] = None,
) -> Tuple[Array, Array, Array, Array, Array, Array, Optional[List[Array]]]:
    """Fold one batch of corpora into the six array states (reference ``chrf.py:376-483``)."""
    if isinstance(preds, str):
        preds = [preds]
    target_: Sequence[Sequence[str]] = [[t] if isinstance(t, str) else t for t in target]

    p_char_add = np.zeros(n_char_order)
    p_word_add = np.zeros(n_word_order)
    t_char_add = np.zeros(n_char_order)
    t_word_add = np.zeros(n_word_order)
    m_char_add = np.zeros(n_char_order)
    m_word_add = np.zeros(n_word_order)

    for pred, targets in zip(preds, target_):
        pred_char_counts, pred_word_counts, pred_char_totals, pred_word_totals = _sentence_statistics(
            pred, n_char_order, n_word_order, lowercase, whitespace
        )
        p_char_add += pred_char_totals
        p_word_add += pred_word_totals

        # Start below any attainable f-score so the first reference's statistics are
        # always recorded, even at zero overlap (else its totals vanish from the corpus
        # recall denominator).
        best_f_score = -1.0
        best_matching_char = np.zeros(n_char_order)
        best_matching_word = np.zeros(n_word_order)
        best_target_char = np.zeros(n_char_order)
        best_target_word = np.zeros(n_word_order)

        for tgt in targets:
            tgt_char_counts, tgt_word_counts, tgt_char_totals, tgt_word_totals = _sentence_statistics(
                tgt, n_char_order, n_word_order, lowercase, whitespace
            )
            matching_char = _matches(pred_char_counts, tgt_char_counts)
            matching_word = _matches(pred_word_counts, tgt_word_counts)
            f_score = _fscore_from_arrays(
                matching_char, matching_word, pred_char_totals, pred_word_totals,
                tgt_char_totals, tgt_word_totals, n_order, beta,
            )
            if f_score > best_f_score:
                best_f_score = f_score
                best_matching_char = matching_char
                best_matching_word = matching_word
                best_target_char = tgt_char_totals
                best_target_word = tgt_word_totals

        t_char_add += best_target_char
        t_word_add += best_target_word
        m_char_add += best_matching_char
        m_word_add += best_matching_word
        if sentence_chrf_score is not None:
            sentence_chrf_score.append(jnp.asarray(best_f_score))

    return (
        total_preds_char_n_grams + jnp.asarray(p_char_add),
        total_preds_word_n_grams + jnp.asarray(p_word_add),
        total_target_char_n_grams + jnp.asarray(t_char_add),
        total_target_word_n_grams + jnp.asarray(t_word_add),
        total_matching_char_n_grams + jnp.asarray(m_char_add),
        total_matching_word_n_grams + jnp.asarray(m_word_add),
        sentence_chrf_score,
    )


def _chrf_score_compute(
    total_preds_char_n_grams: Array,
    total_preds_word_n_grams: Array,
    total_target_char_n_grams: Array,
    total_target_word_n_grams: Array,
    total_matching_char_n_grams: Array,
    total_matching_word_n_grams: Array,
    n_order: float,
    beta: float,
) -> Array:
    """Corpus-level chrF from the accumulated totals (reference ``chrf.py:486-521``)."""
    score = _fscore_from_arrays(
        np.asarray(total_matching_char_n_grams),
        np.asarray(total_matching_word_n_grams),
        np.asarray(total_preds_char_n_grams),
        np.asarray(total_preds_word_n_grams),
        np.asarray(total_target_char_n_grams),
        np.asarray(total_target_word_n_grams),
        n_order,
        beta,
    )
    return jnp.asarray(score)


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """chrF / chrF++ (reference ``chrf.py:524-612``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.chrf import chrf_score
        >>> print(round(float(chrf_score(preds, target)), 4))
        0.5819
    """
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")
    n_order = float(n_char_order + n_word_order)

    states = [
        jnp.zeros(n_char_order),
        jnp.zeros(n_word_order),
        jnp.zeros(n_char_order),
        jnp.zeros(n_word_order),
        jnp.zeros(n_char_order),
        jnp.zeros(n_word_order),
    ]
    sentence_scores: Optional[List[Array]] = [] if return_sentence_level_score else None
    *states, sentence_scores = _chrf_score_update(
        preds, target, *states, n_char_order, n_word_order, n_order, beta, lowercase, whitespace, sentence_scores
    )
    score = _chrf_score_compute(*states, n_order, beta)
    if sentence_scores is not None:
        return score, jnp.stack(sentence_scores)
    return score
