"""Edit-distance core for text metrics (reference ``functional/text/helper.py``).

Host-side by design: tokenization and DP over ragged token sequences are string work
the reference also keeps on host (``helper.py:64``); only the resulting counters land
in device states. The row recurrence is vectorized with numpy — the in-row dependency
``dp[j] = min(dp[j-1]+1, …)`` is a min-plus prefix scan, computed as
``min.accumulate(candidate − j) + j`` — so each row is O(n) numpy ops instead of the
reference's pure-Python O(n) inner loop per cell.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def _token_ids(tokens: Sequence[str], vocab: dict) -> np.ndarray:
    """Map tokens to integer codes (shared vocab dict mutated in place)."""
    return np.asarray([vocab.setdefault(t, len(vocab)) for t in tokens], dtype=np.int64)


def _edit_distance(prediction_tokens: Sequence[str], reference_tokens: Sequence[str]) -> int:
    """Levenshtein distance between token sequences (reference ``helper.py:445-467``)."""
    if len(prediction_tokens) == 0:
        return len(reference_tokens)
    if len(reference_tokens) == 0:
        return len(prediction_tokens)
    vocab: dict = {}
    a = _token_ids(prediction_tokens, vocab)
    b = _token_ids(reference_tokens, vocab)

    n = b.shape[0]
    j_range = np.arange(n + 1)
    prev = j_range.copy()
    for i, ca in enumerate(a, start=1):
        cost = (b != ca).astype(np.int64)
        m = np.empty(n + 1, dtype=np.int64)
        m[0] = i
        np.minimum(prev[1:] + 1, prev[:-1] + cost, out=m[1:])
        # deletion chain: dp[j] = min_{k<=j} m[k] + (j-k)  — min-plus prefix scan
        cur = np.minimum.accumulate(m - j_range) + j_range
        prev = cur
    return int(prev[-1])
