"""BERTScore (reference ``functional/text/bert.py``).

The greedy cosine-matching core is pure jnp — one (L_p, L_t) matmul per pair, vmapped
over the batch (MXU path). The transformer comes from either path the reference
supports: ``model_name_or_path`` loads a HF transformer (Flax-first via
``utilities.hf``, torch-weight conversion, offline-clean error when the weights are
not cached — reference ``text/bert.py:192-195``), or inject ``user_tokenizer``
(sentences → {input_ids, attention_mask}) plus ``model`` (input_ids, attention_mask →
(N, L, D) embeddings) like the reference's own-model path
(``examples/bert_score-own_model.py``).
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _validate_model_inputs(model: Any, user_tokenizer: Any) -> None:
    if model is None or isinstance(model, str):
        raise ModuleNotFoundError(
            f"Default transformer backbones (`model_name_or_path={model!r}`) require downloadable pretrained"
            " weights, which are not available. Pass a callable `model(input_ids, attention_mask) -> embeddings`"
            " plus a `user_tokenizer`, as in the reference's own-model example."
        )
    if not callable(model):
        raise ValueError("Argument `model` must be a callable embedding model.")
    if user_tokenizer is None or not callable(user_tokenizer):
        raise ValueError("A callable `user_tokenizer` returning {'input_ids', 'attention_mask'} is required.")


def _compute_idf(token_batches: List[Array], mask_batches: List[Array]) -> Dict[int, float]:
    """Inverse document frequency over the target corpus (reference ``bert.py`` idf path)."""
    import numpy as np

    doc_counts: Counter = Counter()
    num_docs = 0
    for ids, mask in zip(token_batches, mask_batches):
        ids_np = np.asarray(ids)
        mask_np = np.asarray(mask).astype(bool)
        for row, mrow in zip(ids_np, mask_np):
            num_docs += 1
            doc_counts.update(set(row[mrow].tolist()))
    import math

    return {tok: math.log((num_docs + 1) / (cnt + 1)) for tok, cnt in doc_counts.items()}


def _idf_weights(ids: Array, mask: Array, idf: Optional[Dict[int, float]]) -> Array:
    """Per-token weights: idf lookup or uniform."""
    import numpy as np

    if idf is None:
        return jnp.asarray(np.asarray(mask), dtype=jnp.float32)
    ids_np = np.asarray(ids)
    default = 0.0
    w = np.vectorize(lambda t: idf.get(int(t), default))(ids_np).astype(np.float32)
    return jnp.asarray(w) * jnp.asarray(np.asarray(mask), dtype=jnp.float32)


def _greedy_cosine_scores(
    pred_emb: Array, pred_mask: Array, tgt_emb: Array, tgt_mask: Array, pred_w: Array, tgt_w: Array
) -> Tuple[Array, Array, Array]:
    """Batched precision/recall/F1 from greedy token matching.

    pred_emb: (N, Lp, D); tgt_emb: (N, Lt, D); masks/weights (N, L*).
    """

    def _norm(e):
        return e / jnp.clip(jnp.linalg.norm(e, axis=-1, keepdims=True), 1e-12)

    pred_n = _norm(pred_emb)
    tgt_n = _norm(tgt_emb)

    def _one(pe, pm, te, tm, pw, tw):
        # full-f32 matmul: at TPU-default (bf16) precision an identical pair's
        # self-similarity lands at ~0.9995 instead of 1.0 — metric fidelity is
        # worth the negligible cost next to the model forward
        sim = jnp.matmul(pe, te.T, precision=jax.lax.Precision.HIGHEST)  # (Lp, Lt)
        neg = -jnp.inf
        sim_masked = jnp.where(pm[:, None] * tm[None, :] > 0, sim, neg)
        best_for_pred = jnp.where(pm > 0, jnp.max(sim_masked, axis=1), 0.0)
        best_for_tgt = jnp.where(tm > 0, jnp.max(sim_masked, axis=0), 0.0)
        precision = jnp.sum(best_for_pred * pw) / jnp.clip(jnp.sum(pw), 1e-12)
        recall = jnp.sum(best_for_tgt * tw) / jnp.clip(jnp.sum(tw), 1e-12)
        f1 = 2 * precision * recall / jnp.clip(precision + recall, 1e-12)
        return precision, recall, f1

    return jax.vmap(_one)(pred_n, pred_mask, tgt_n, tgt_mask, pred_w, tgt_w)


def _resolve_model_and_tokenizer(
    model_name_or_path: Optional[str],
    num_layers: Optional[int],
    model: Optional[Callable],
    user_tokenizer: Optional[Callable],
    max_length: int,
) -> Tuple[Optional[Callable], Optional[Callable], int]:
    """Resolve ``(forward, tokenizer, pad_width)`` for the HF path.

    Reference ``text/bert.py:192-195``: Flax-first transformer + AutoTokenizer with
    offline-clean errors (utilities.hf). The tokenizer pads to the model-capped
    ``pad_width`` so every batch has the same width — which is what lets the
    modular metric store tokenized ARRAYS that ride the cross-process gather.
    """
    pad_width = max_length
    if model is None and model_name_or_path is not None:
        from torchmetrics_tpu.utilities.hf import (
            hf_embedding_forward,
            hf_tokenize,
            load_hf_model_and_tokenizer,
            model_max_length,
        )

        hf_model, hf_tok = load_hf_model_and_tokenizer(model_name_or_path)
        model = hf_embedding_forward(hf_model, num_layers=num_layers)
        pad_width = model_max_length(hf_model, max_length)
        if user_tokenizer is None:
            hf_max_length = pad_width
            user_tokenizer = lambda sents: dict(  # noqa: E731
                zip(("input_ids", "attention_mask"), hf_tokenize(hf_tok, sents, max_length=hf_max_length))
            )
    return model, user_tokenizer, pad_width


def _score_from_tokens(
    pred_tok: Dict[str, Array],
    tgt_tok: Dict[str, Array],
    forward: Callable,
    idf: bool,
) -> Tuple[Array, Array, Array]:
    """(precision, recall, f1) per pair from tokenized batches — the post-tokenize
    half of the pipeline, shared by the functional API and the modular metric's
    tokenized-tensor states."""
    pred_emb = forward(pred_tok["input_ids"], pred_tok["attention_mask"])
    tgt_emb = forward(tgt_tok["input_ids"], tgt_tok["attention_mask"])

    idf_map = (
        _compute_idf([tgt_tok["input_ids"]], [tgt_tok["attention_mask"]]) if idf else None
    )
    pred_w = _idf_weights(pred_tok["input_ids"], pred_tok["attention_mask"], idf_map)
    tgt_w = _idf_weights(tgt_tok["input_ids"], tgt_tok["attention_mask"], idf_map)

    return _greedy_cosine_scores(
        pred_emb,
        jnp.asarray(pred_tok["attention_mask"], dtype=jnp.float32),
        tgt_emb,
        jnp.asarray(tgt_tok["attention_mask"], dtype=jnp.float32),
        pred_w,
        tgt_w,
    )


def bert_score(
    preds: Union[str, List[str]],
    target: Union[str, List[str]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Callable] = None,
    user_tokenizer: Optional[Callable] = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 4,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
) -> Dict[str, Union[Array, List[float], str]]:
    """BERTScore with an injected embedding model (reference ``bert.py:...``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("Number of predicted and reference sentences must be the same!")
    if rescale_with_baseline:
        raise ValueError("Baseline rescaling requires downloadable baseline files, which are unavailable.")
    model, user_tokenizer, _ = _resolve_model_and_tokenizer(
        model_name_or_path, num_layers, model, user_tokenizer, max_length
    )
    _validate_model_inputs(model if model is not None else model_name_or_path, user_tokenizer)

    pred_tok = user_tokenizer(preds)
    tgt_tok = user_tokenizer(target)
    forward = user_forward_fn if user_forward_fn is not None else model
    precision, recall, f1 = _score_from_tokens(pred_tok, tgt_tok, forward, idf)
    return {"precision": precision, "recall": recall, "f1": f1}
