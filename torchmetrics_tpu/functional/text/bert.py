"""BERTScore (reference ``functional/text/bert.py``).

The greedy cosine-matching core is pure jnp — one (L_p, L_t) matmul per pair, vmapped
over the batch (MXU path). The transformer comes from either path the reference
supports: ``model_name_or_path`` loads a HF transformer (Flax-first via
``utilities.hf``, torch-weight conversion, offline-clean error when the weights are
not cached — reference ``text/bert.py:192-195``), or inject ``user_tokenizer``
(sentences → {input_ids, attention_mask}) plus ``model`` (input_ids, attention_mask →
(N, L, D) embeddings) like the reference's own-model path
(``examples/bert_score-own_model.py``).
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

_BERT_BUCKETS_ENV = "TORCHMETRICS_TPU_BERT_BUCKETS"


def bert_buckets_enabled() -> bool:
    """Whether BERTScore stages ragged batches through power-of-two shape buckets.

    On (the default), every tokenized batch pads its pair count and token
    widths up to the engine's power-of-two buckets BEFORE the model forward and
    the jitted greedy-cosine core, so a ragged eval stream compiles O(log N ·
    log L) score graphs instead of one per distinct shape — and the IDF
    weighting stays a device-side table gather (zero host touches in the score
    path). ``TORCHMETRICS_TPU_BERT_BUCKETS=0|off`` restores exact-shape
    staging; unrecognized values fail loud (the PR-7 env contract).
    """
    raw = os.environ.get(_BERT_BUCKETS_ENV, "").strip().lower()
    if raw in ("", "1", "on"):
        return True
    if raw in ("0", "off"):
        return False
    from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

    raise TorchMetricsUserError(
        f"{_BERT_BUCKETS_ENV} must be unset/'1'/'on' or '0'/'off' (got {raw!r})"
    )


def _validate_model_inputs(model: Any, user_tokenizer: Any) -> None:
    if model is None or isinstance(model, str):
        raise ModuleNotFoundError(
            f"Default transformer backbones (`model_name_or_path={model!r}`) require downloadable pretrained"
            " weights, which are not available. Pass a callable `model(input_ids, attention_mask) -> embeddings`"
            " plus a `user_tokenizer`, as in the reference's own-model example."
        )
    if not callable(model):
        raise ValueError("Argument `model` must be a callable embedding model.")
    if user_tokenizer is None or not callable(user_tokenizer):
        raise ValueError("A callable `user_tokenizer` returning {'input_ids', 'attention_mask'} is required.")


def _compute_idf(token_batches: List[Array], mask_batches: List[Array]) -> Dict[int, float]:
    """Inverse document frequency over the target corpus (reference ``bert.py`` idf path)."""
    import numpy as np

    doc_counts: Counter = Counter()
    num_docs = 0
    for ids, mask in zip(token_batches, mask_batches):
        ids_np = np.asarray(ids)
        mask_np = np.asarray(mask).astype(bool)
        for row, mrow in zip(ids_np, mask_np):
            num_docs += 1
            doc_counts.update(set(row[mrow].tolist()))
    import math

    return {tok: math.log((num_docs + 1) / (cnt + 1)) for tok, cnt in doc_counts.items()}


def _idf_table(idf: Dict[int, float]) -> Tuple[Array, Array]:
    """``(sorted_token_ids, weights)`` device arrays for the vectorized gather.

    Built once per corpus dict; the per-token lookup then lowers to one
    ``searchsorted`` + gather on device — the host-Python ``np.vectorize``
    walk this replaces cost O(tokens) Python calls per batch, scaling with
    corpus size.
    """
    import numpy as np

    keys = np.fromiter(sorted(idf), dtype=np.int64, count=len(idf))
    vals = np.asarray([idf[int(k)] for k in keys], dtype=np.float32)
    if keys.size == 0:  # empty corpus: a 1-slot miss table keeps shapes static
        keys = np.asarray([-1], dtype=np.int64)
        vals = np.zeros(1, dtype=np.float32)
    return jnp.asarray(keys), jnp.asarray(vals)


def _idf_weights(
    ids: Array, mask: Array, table: Optional[Union[Dict[int, float], Tuple[Array, Array]]]
) -> Array:
    """Per-token weights: device-side idf table gather, or uniform (the mask).

    Tokens absent from the corpus table weight 0.0 — the same default the old
    host-side ``dict.get`` lookup applied, now as a binary-search gather that
    never leaves the device. Accepts either the prebuilt ``_idf_table`` pair
    (build it once per corpus) or the raw idf dict (legacy callers — infolm).
    """
    mask_f = jnp.asarray(mask, dtype=jnp.float32)
    if table is None:
        return mask_f
    if isinstance(table, dict):
        table = _idf_table(table)
    keys, vals = table
    ids_j = jnp.asarray(ids, dtype=keys.dtype)
    pos = jnp.clip(jnp.searchsorted(keys, ids_j), 0, keys.shape[0] - 1)
    w = jnp.where(keys[pos] == ids_j, vals[pos], 0.0)
    return w * mask_f


def _greedy_cosine_scores(
    pred_emb: Array, pred_mask: Array, tgt_emb: Array, tgt_mask: Array, pred_w: Array, tgt_w: Array
) -> Tuple[Array, Array, Array]:
    """Batched precision/recall/F1 from greedy token matching.

    pred_emb: (N, Lp, D); tgt_emb: (N, Lt, D); masks/weights (N, L*).
    """

    def _norm(e):
        return e / jnp.clip(jnp.linalg.norm(e, axis=-1, keepdims=True), 1e-12)

    pred_n = _norm(pred_emb)
    tgt_n = _norm(tgt_emb)

    def _one(pe, pm, te, tm, pw, tw):
        # full-f32 matmul: at TPU-default (bf16) precision an identical pair's
        # self-similarity lands at ~0.9995 instead of 1.0 — metric fidelity is
        # worth the negligible cost next to the model forward
        sim = jnp.matmul(pe, te.T, precision=jax.lax.Precision.HIGHEST)  # (Lp, Lt)
        neg = -jnp.inf
        sim_masked = jnp.where(pm[:, None] * tm[None, :] > 0, sim, neg)
        best_for_pred = jnp.where(pm > 0, jnp.max(sim_masked, axis=1), 0.0)
        best_for_tgt = jnp.where(tm > 0, jnp.max(sim_masked, axis=0), 0.0)
        precision = jnp.sum(best_for_pred * pw) / jnp.clip(jnp.sum(pw), 1e-12)
        recall = jnp.sum(best_for_tgt * tw) / jnp.clip(jnp.sum(tw), 1e-12)
        f1 = 2 * precision * recall / jnp.clip(precision + recall, 1e-12)
        return precision, recall, f1

    return jax.vmap(_one)(pred_n, pred_mask, tgt_n, tgt_mask, pred_w, tgt_w)


#: the compiled score core — with bucketed staging its compile-signature count
#: is bounded by O(log N · log L) for an arbitrarily ragged eval stream
_scores_jit = jax.jit(_greedy_cosine_scores)


def bert_scoring_cache_size() -> int:
    """Compiled score-graph signatures held by the jitted greedy-cosine core.

    The retrace evidence surface for the bucketing contract: a ragged stream
    staged through the shape buckets holds this at O(log N · log L) — bench and
    tests assert it stops growing once the bucket set is warm.
    """
    return int(_scores_jit._cache_size())


def _pad_2d(arr: Array, rows: int, width: int) -> Array:
    """Zero-pad a (N, L) batch up to the bucketed shape (mask-neutral)."""
    arr = jnp.asarray(arr)
    pad_r, pad_w = rows - arr.shape[0], width - arr.shape[1]
    if pad_r or pad_w:
        arr = jnp.pad(arr, ((0, pad_r), (0, pad_w)))
    return arr


def _resolve_model_and_tokenizer(
    model_name_or_path: Optional[str],
    num_layers: Optional[int],
    model: Optional[Callable],
    user_tokenizer: Optional[Callable],
    max_length: int,
) -> Tuple[Optional[Callable], Optional[Callable], int]:
    """Resolve ``(forward, tokenizer, pad_width)`` for the HF path.

    Reference ``text/bert.py:192-195``: Flax-first transformer + AutoTokenizer with
    offline-clean errors (utilities.hf). The tokenizer pads to the model-capped
    ``pad_width`` so every batch has the same width — which is what lets the
    modular metric store tokenized ARRAYS that ride the cross-process gather.
    """
    pad_width = max_length
    if model is None and model_name_or_path is not None:
        from torchmetrics_tpu.utilities.hf import (
            hf_embedding_forward,
            hf_tokenize,
            load_hf_model_and_tokenizer,
            model_max_length,
        )

        hf_model, hf_tok = load_hf_model_and_tokenizer(model_name_or_path)
        model = hf_embedding_forward(hf_model, num_layers=num_layers)
        pad_width = model_max_length(hf_model, max_length)
        if user_tokenizer is None:
            hf_max_length = pad_width
            user_tokenizer = lambda sents: dict(  # noqa: E731
                zip(("input_ids", "attention_mask"), hf_tokenize(hf_tok, sents, max_length=hf_max_length))
            )
    return model, user_tokenizer, pad_width


def _score_from_tokens(
    pred_tok: Dict[str, Array],
    tgt_tok: Dict[str, Array],
    forward: Callable,
    idf: bool,
) -> Tuple[Array, Array, Array]:
    """(precision, recall, f1) per pair from tokenized batches — the post-tokenize
    half of the pipeline, shared by the functional API and the modular metric's
    tokenized-tensor states.

    With bucketing on (the default), the pair count and token widths pad up to
    the engine's power-of-two buckets BEFORE the model forward and the jitted
    score core: a ragged eval stream reuses O(log N · log L) compiled graphs,
    and zero-mask pad rows/columns are score-neutral (sliced off the result).
    """
    # corpus idf over the RAW, UNPADDED target arrays: bucket-pad rows would
    # inflate the document count, and counting happens BEFORE any device
    # conversion so a numpy-returning tokenizer stays host-pure (no round-trip)
    table = (
        _idf_table(_compute_idf([tgt_tok["input_ids"]], [tgt_tok["attention_mask"]]))
        if idf
        else None
    )

    pred_ids = jnp.asarray(pred_tok["input_ids"])
    pred_mask = jnp.asarray(pred_tok["attention_mask"])
    tgt_ids = jnp.asarray(tgt_tok["input_ids"])
    tgt_mask = jnp.asarray(tgt_tok["attention_mask"])
    n = pred_ids.shape[0]

    if bert_buckets_enabled():
        from torchmetrics_tpu.engine import bucketing

        rows = bucketing.next_bucket(max(n, 1))
        lp = bucketing.next_bucket(max(pred_ids.shape[1], 1))
        lt = bucketing.next_bucket(max(tgt_ids.shape[1], 1))
        pred_ids, pred_mask = _pad_2d(pred_ids, rows, lp), _pad_2d(pred_mask, rows, lp)
        tgt_ids, tgt_mask = _pad_2d(tgt_ids, rows, lt), _pad_2d(tgt_mask, rows, lt)

    pred_emb = forward(pred_ids, pred_mask)
    tgt_emb = forward(tgt_ids, tgt_mask)
    pred_w = _idf_weights(pred_ids, pred_mask, table)
    tgt_w = _idf_weights(tgt_ids, tgt_mask, table)

    precision, recall, f1 = _scores_jit(
        pred_emb,
        jnp.asarray(pred_mask, dtype=jnp.float32),
        tgt_emb,
        jnp.asarray(tgt_mask, dtype=jnp.float32),
        pred_w,
        tgt_w,
    )
    return precision[:n], recall[:n], f1[:n]


def bert_score(
    preds: Union[str, List[str]],
    target: Union[str, List[str]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Callable] = None,
    user_tokenizer: Optional[Callable] = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 4,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
) -> Dict[str, Union[Array, List[float], str]]:
    """BERTScore with an injected embedding model (reference ``bert.py:...``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError("Number of predicted and reference sentences must be the same!")
    if rescale_with_baseline:
        raise ValueError("Baseline rescaling requires downloadable baseline files, which are unavailable.")
    model, user_tokenizer, _ = _resolve_model_and_tokenizer(
        model_name_or_path, num_layers, model, user_tokenizer, max_length
    )
    _validate_model_inputs(model if model is not None else model_name_or_path, user_tokenizer)

    pred_tok = user_tokenizer(preds)
    tgt_tok = user_tokenizer(target)
    forward = user_forward_fn if user_forward_fn is not None else model
    precision, recall, f1 = _score_from_tokens(pred_tok, tgt_tok, forward, idf)
    return {"precision": precision, "recall": recall, "f1": f1}
