"""SQuAD EM/F1 (reference ``functional/text/squad.py``)."""

from __future__ import annotations

import re
import string
from collections import Counter
from typing import Any, Callable, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

SINGLE_PRED_TYPE = Dict[str, Any]
PREDS_TYPE = Union[SINGLE_PRED_TYPE, List[SINGLE_PRED_TYPE]]
SINGLE_TARGET_TYPE = Dict[str, Any]
TARGETS_TYPE = Union[SINGLE_TARGET_TYPE, List[SINGLE_TARGET_TYPE]]


def _normalize_text(s: str) -> str:
    """Lowercase, strip punctuation/articles/extra whitespace (reference ``squad.py:41-60``)."""

    def remove_articles(text: str) -> str:
        return re.sub(r"\b(a|an|the)\b", " ", text)

    def white_space_fix(text: str) -> str:
        return " ".join(text.split())

    def remove_punc(text: str) -> str:
        exclude = set(string.punctuation)
        return "".join(ch for ch in text if ch not in exclude)

    return white_space_fix(remove_articles(remove_punc(s.lower())))


def _get_tokens(s: str) -> List[str]:
    """Normalized tokens (reference ``squad.py:63-65``)."""
    return _normalize_text(s).split() if s else []


def _compute_f1_score(predicted_answer: str, target_answer: str) -> Array:
    """Token-overlap F1 (reference ``squad.py:68-82``)."""
    target_tokens = _get_tokens(target_answer)
    predicted_tokens = _get_tokens(predicted_answer)
    common = Counter(target_tokens) & Counter(predicted_tokens)
    num_same = jnp.asarray(sum(common.values()), dtype=jnp.float32)
    if len(target_tokens) == 0 or len(predicted_tokens) == 0:
        # If either is no-answer, F1 is 1 if they agree, 0 otherwise
        return jnp.asarray(float(target_tokens == predicted_tokens))
    if num_same == 0:
        return jnp.asarray(0.0)
    precision = 1.0 * num_same / len(predicted_tokens)
    recall = 1.0 * num_same / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _compute_exact_match_score(prediction: str, ground_truth: str) -> Array:
    """Exact match after normalization (reference ``squad.py:85-87``)."""
    return jnp.asarray(float(_normalize_text(prediction) == _normalize_text(ground_truth)))


def _metric_max_over_ground_truths(
    metric_fn: Callable[[str, str], Array], prediction: str, ground_truths: List[str]
) -> Array:
    """Best score over multiple ground truths (reference ``squad.py:90-95``)."""
    return jnp.max(jnp.stack([metric_fn(prediction, truth) for truth in ground_truths]))


def _squad_input_check(
    preds: PREDS_TYPE, targets: TARGETS_TYPE
) -> Tuple[Dict[str, str], List[Dict[str, List[Dict[str, List[Any]]]]]]:
    """Normalize input formats (reference ``squad.py:98-147``)."""
    if isinstance(preds, Dict):
        preds = [preds]
    if isinstance(targets, Dict):
        targets = [targets]

    for pred in preds:
        keys = pred.keys()
        if "prediction_text" not in keys or "id" not in keys:
            raise KeyError(f"Expected keys in a single prediction are 'prediction_text' and 'id'. Got {keys}")
    for target in targets:
        keys = target.keys()
        if "answers" not in keys or "id" not in keys:
            raise KeyError(f"Expected keys in a single target are 'answers' and 'id'. Got {keys}")
        answers_keys = target["answers"].keys()
        if "text" not in answers_keys:
            raise KeyError(f"Expected keys in a 'answers' are 'text'. Got {answers_keys}")

    preds_dict = {prediction["id"]: prediction["prediction_text"] for prediction in preds}
    _fn_answer = lambda tgt: {"answers": [{"text": txt} for txt in tgt["answers"]["text"]], "id": tgt["id"]}
    targets_dict = [{"paragraphs": [{"qas": [_fn_answer(target) for target in targets]}]}]
    return preds_dict, targets_dict


def _squad_update(
    preds: Dict[str, str],
    target: List[Dict[str, List[Dict[str, List[Any]]]]],
) -> Tuple[Array, Array, Array]:
    """Σ f1, Σ exact_match, count (reference ``squad.py:150-193``)."""
    f1 = jnp.asarray(0.0)
    exact_match = jnp.asarray(0.0)
    total = 0
    for article in target:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in preds:
                    continue
                ground_truths = [x["text"] for x in qa["answers"]]
                pred = preds[qa["id"]]
                exact_match = exact_match + _metric_max_over_ground_truths(
                    _compute_exact_match_score, pred, ground_truths
                )
                f1 = f1 + _metric_max_over_ground_truths(_compute_f1_score, pred, ground_truths)
    return f1, exact_match, jnp.asarray(total)


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    """Mean EM/F1 in percent (reference ``squad.py:196-211``)."""
    return {"exact_match": 100.0 * exact_match / total, "f1": 100.0 * f1 / total}


def squad(preds: PREDS_TYPE, target: TARGETS_TYPE) -> Dict[str, Array]:
    """SQuAD EM/F1 (reference ``squad.py:214-260``)."""
    preds_dict, target_dict = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, target_dict)
    return _squad_compute(f1, exact_match, total)
