"""Word information preserved (reference ``functional/text/wip.py``)."""

from __future__ import annotations

from typing import List, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.text.helper import _edit_distance

Array = jax.Array


def _wip_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array, Array]:
    """(Σedit − Σmax_len, Σ ref words, Σ pred words) (reference ``wip.py:23-56``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    total = 0
    errors = 0
    target_total = 0
    preds_total = 0
    for pred, tgt in zip(preds, target):
        pred_tokens = pred.split()
        target_tokens = tgt.split()
        errors += _edit_distance(pred_tokens, target_tokens)
        target_total += len(target_tokens)
        preds_total += len(pred_tokens)
        total += max(len(target_tokens), len(pred_tokens))
    return jnp.asarray(float(errors - total)), jnp.asarray(float(target_total)), jnp.asarray(float(preds_total))


def _wip_compute(errors: Array, target_total: Array, preds_total: Array) -> Array:
    """Reference ``wip.py:59-71``."""
    return (errors / target_total) * (errors / preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """WIP (reference ``wip.py:74-97``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.wip import word_information_preserved
        >>> print(round(float(word_information_preserved(preds, target)), 4))
        0.6806
    """
    errors, target_total, preds_total = _wip_update(preds, target)
    return _wip_compute(errors, target_total, preds_total)
