"""BLEU score (reference ``functional/text/bleu.py``).

N-gram counting on host tokens → fixed per-order tensor states (numerator/denominator
of shape (n_gram,), sum-reduced — one psum at sync, like the reference
``text/bleu.py:90-93``); the geometric-mean/brevity-penalty compute is jnp.
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def _count_ngram(ngram_input_list: Sequence[str], n_gram: int) -> Counter:
    """Counter over all 1..n grams (reference ``bleu.py:21-37``)."""
    ngram_counter: Counter = Counter()
    for i in range(1, n_gram + 1):
        for j in range(len(ngram_input_list) - i + 1):
            ngram_counter[tuple(ngram_input_list[j : i + j])] += 1
    return ngram_counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    """Whitespace tokenizer (reference ``bleu.py:40-49``)."""
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    numerator: Array,
    denominator: Array,
    preds_len: Array,
    target_len: Array,
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Fold one batch of corpora into the four states (reference ``bleu.py:52-98``).

    Returns all four updated states (the reference mutates numerator/denominator in
    place; immutable arrays here).
    """
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok = [tokenizer(line) if line else [] for line in preds]

    num_add = [0.0] * n_gram
    den_add = [0.0] * n_gram
    preds_len_add = 0.0
    target_len_add = 0.0
    for pred, targets in zip(preds_tok, target_tok):
        preds_len_add += len(pred)
        target_len_list = [len(tgt) for tgt in targets]
        target_len_diff = [abs(len(pred) - x) for x in target_len_list]
        target_len_add += target_len_list[target_len_diff.index(min(target_len_diff))]
        preds_counter: Counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)

        ngram_counter_clip = preds_counter & target_counter
        for counter_clip in ngram_counter_clip:
            num_add[len(counter_clip) - 1] += ngram_counter_clip[counter_clip]
        for counter in preds_counter:
            den_add[len(counter) - 1] += preds_counter[counter]

    numerator = numerator + jnp.asarray(num_add)
    denominator = denominator + jnp.asarray(den_add)
    preds_len = preds_len + preds_len_add
    target_len = target_len + target_len_add
    return numerator, denominator, preds_len, target_len


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    """Weighted-log-precision BLEU with brevity penalty (reference ``bleu.py:101-135``)."""
    # Stay on-device: a float() fetch here would poison the axon stream for every
    # subsequent op in a forward() loop. Mask the zero-count branch with where instead.
    min_numerator = jnp.min(numerator)
    denominator_safe = jnp.where(denominator == 0, 1.0, denominator)
    if smooth:
        precision_scores = (numerator + 1.0) / (denominator + 1.0)
        precision_scores = precision_scores.at[0].set(numerator[0] / denominator_safe[0])
    else:
        precision_scores = numerator / denominator_safe

    precision_safe = jnp.where(precision_scores > 0, precision_scores, 1.0)
    log_precision_scores = jnp.asarray(weights) * jnp.log(precision_safe)
    geometric_mean = jnp.exp(jnp.sum(log_precision_scores))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - (target_len / preds_len)))
    return jnp.where(min_numerator == 0, jnp.asarray(0.0), brevity_penalty * geometric_mean)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """BLEU (reference ``bleu.py:138-195``).

    Example:
        >>> preds = ['the cat sat on the mat', 'hello world']
        >>> target = ['the cat sat on a mat', 'hello there world']
        >>> from torchmetrics_tpu.functional.text.bleu import bleu_score
        >>> print(round(float(bleu_score(preds, target)), 4))
        0.4586
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator = jnp.zeros(n_gram)
    denominator = jnp.zeros(n_gram)
    preds_len = jnp.asarray(0.0)
    target_len = jnp.asarray(0.0)
    numerator, denominator, preds_len, target_len = _bleu_score_update(
        preds_, target_, numerator, denominator, preds_len, target_len, n_gram
    )
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
