"""Pairwise minkowski distance (reference ``functional/pairwise/minkowski.py``)."""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

Array = jax.Array


def _pairwise_minkowski_distance_update(
    x: Array,
    y: Optional[Array] = None,
    exponent: Union[int, float] = 2,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Broadcasted p-norm distance (reference ``minkowski.py:24-46``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if not (isinstance(exponent, (float, int)) and exponent >= 1):
        raise TorchMetricsUserError(
            f"Argument ``p`` must be a float or int greater than or equal to 1, but got {exponent}"
        )
    distance = (jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent).sum(axis=-1) ** (1.0 / exponent)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_minkowski_distance(
    x: Array,
    y: Optional[Array] = None,
    exponent: Union[int, float] = 2,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    r"""Pairwise minkowski distances between rows of ``x`` (and ``y``) (reference ``minkowski.py:48-94``)."""
    distance = _pairwise_minkowski_distance_update(x, y, exponent, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
