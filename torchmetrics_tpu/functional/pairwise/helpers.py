"""Shared input checks and reductions for the pairwise matrices (reference ``functional/pairwise/helpers.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_input(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Tuple[Array, Array, bool]:
    """Validate shapes and resolve the ``zero_diagonal`` default (reference ``helpers.py:18-44``)."""
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")

    if y is not None:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """Row-wise mean/sum or the full matrix (reference ``helpers.py:47-61``)."""
    if reduction == "mean":
        return distmat.mean(axis=-1)
    if reduction == "sum":
        return distmat.sum(axis=-1)
    if reduction is None or reduction == "none":
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")


def _zero_diagonal(distmat: Array, zero_diagonal: bool) -> Array:
    """Branch-free diagonal clear so the kernels stay jit-friendly."""
    if not zero_diagonal:
        return distmat
    n, m = distmat.shape
    eye = jnp.eye(n, m, dtype=bool)
    return jnp.where(eye, 0.0, distmat)
