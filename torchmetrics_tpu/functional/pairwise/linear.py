"""Pairwise linear similarity (reference ``functional/pairwise/linear.py``)."""

from __future__ import annotations

from typing import Optional

import jax

from torchmetrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_linear_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Plain inner-product matrix ``x y^T`` (reference ``linear.py:22-38``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = x @ y.T
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_linear_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    r"""Pairwise linear similarity between rows of ``x`` (and ``y``) (reference ``linear.py:41-84``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        >>> target = jnp.asarray([[1.0, 2.5], [2.5, 4.0], [5.5, 6.5]])
        >>> from torchmetrics_tpu.functional.pairwise.linear import pairwise_linear_similarity
        >>> print(pairwise_linear_similarity(preds, target).shape)
        (3, 3)
    """
    distance = _pairwise_linear_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
