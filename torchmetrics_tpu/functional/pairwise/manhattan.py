"""Pairwise manhattan distance (reference ``functional/pairwise/manhattan.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_manhattan_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Broadcasted |x_i - y_j| sum (reference ``manhattan.py:22-38``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.abs(x[:, None, :] - y[None, :, :]).sum(axis=-1)
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_manhattan_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    r"""Pairwise manhattan distances between rows of ``x`` (and ``y``) (reference ``manhattan.py:41-85``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        >>> target = jnp.asarray([[1.0, 2.5], [2.5, 4.0], [5.5, 6.5]])
        >>> from torchmetrics_tpu.functional.pairwise.manhattan import pairwise_manhattan_distance
        >>> print(pairwise_manhattan_distance(preds, target).shape)
        (3, 3)
    """
    distance = _pairwise_manhattan_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
