"""Pairwise euclidean distance (reference ``functional/pairwise/euclidean.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """``sqrt(|x|^2 + |y|^2 - 2 x y^T)`` — one MXU matmul plus row norms (reference ``euclidean.py:22-42``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    # accumulate the norm algebra in f64 on host platforms that allow it; the matmul
    # itself is the MXU-friendly part (reference upcasts for the same cancellation issue)
    dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    xd = x.astype(dtype)
    yd = y.astype(dtype)
    x_norm = (xd * xd).sum(axis=1, keepdims=True)
    y_norm = (yd * yd).sum(axis=1)
    distance = (x_norm + y_norm - 2 * xd @ yd.T).astype(x.dtype)
    distance = _zero_diagonal(distance, zero_diagonal)
    return jnp.sqrt(jnp.clip(distance, 0.0))


def pairwise_euclidean_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    r"""Pairwise euclidean distances between rows of ``x`` (and ``y``) (reference ``euclidean.py:45-89``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.pairwise import pairwise_euclidean_distance
        >>> x = jnp.asarray([[0.0, 0.0], [3.0, 4.0]])
        >>> pairwise_euclidean_distance(x).round(1).tolist()
        [[0.0, 5.0], [5.0, 0.0]]
    """
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
