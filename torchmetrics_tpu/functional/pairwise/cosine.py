"""Pairwise cosine similarity (reference ``functional/pairwise/cosine.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal

Array = jax.Array


def _pairwise_cosine_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Row-normalize then one matmul (reference ``cosine.py:24-45``)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    norm_x = jnp.linalg.norm(x, axis=1, keepdims=True)
    norm_y = jnp.linalg.norm(y, axis=1, keepdims=True)
    x_normed = x / jnp.where(norm_x == 0, 1.0, norm_x)
    y_normed = y / jnp.where(norm_y == 0, 1.0, norm_y)
    distance = x_normed @ y_normed.T
    return _zero_diagonal(distance, zero_diagonal)


def pairwise_cosine_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    r"""Pairwise cosine similarity between rows of ``x`` (and ``y``) (reference ``cosine.py:48-93``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pairwise_cosine_similarity
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> print(jnp.round(pairwise_cosine_similarity(x, y), 4))
        [[0.5547 0.8682]
         [0.5145 0.8437]
         [0.53   0.8533]]
    """
    distance = _pairwise_cosine_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
