"""Relative squared error (reference ``functional/regression/rse.py``)."""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.r2 import _r2_score_update

Array = jax.Array


def _relative_squared_error_compute(
    sum_squared_obs: Array,
    sum_obs: Array,
    sum_squared_error: Array,
    n_obs: Union[int, Array],
    squared: bool = True,
) -> Array:
    """RSE = Σ(y−ŷ)² / Σ(y−ȳ)² (reference ``rse.py:22-45``)."""
    epsilon = jnp.finfo(jnp.float32).eps
    rse = sum_squared_error / jnp.clip(sum_squared_obs - sum_obs * sum_obs / n_obs, epsilon, None)
    if not squared:
        rse = jnp.sqrt(rse)
    return jnp.mean(rse)


def relative_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """RSE (reference ``rse.py:48-77``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.rse import relative_squared_error
        >>> print(round(float(relative_squared_error(preds, target)), 4))
        0.0514
    """
    sum_squared_obs, sum_obs, rss, n_obs = _r2_score_update(preds, target)
    return _relative_squared_error_compute(sum_squared_obs, sum_obs, rss, n_obs, squared=squared)
