"""Log-cosh error (reference ``functional/regression/log_cosh.py``)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs
from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _unsqueeze_tensors(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``log_cosh.py:23-26``."""
    if preds.ndim == 2:
        return preds, target
    return preds[:, None], target[:, None]


def _log_cosh_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    """Σ log(cosh(err)) per output + count (reference ``log_cosh.py:29-49``)."""
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    preds, target = _unsqueeze_tensors(preds, target)
    diff = preds - target
    # numerically-stable log(cosh(x)) = x + softplus(-2x) - log(2)
    sum_log_cosh_error = jnp.sum(diff + jax.nn.softplus(-2 * diff) - jnp.log(2.0), axis=0).squeeze()
    return sum_log_cosh_error, jnp.asarray(preds.shape[0], dtype=jnp.int32)


def _log_cosh_error_compute(sum_log_cosh_error: Array, n_obs: Array) -> Array:
    """Reference ``log_cosh.py:52-55``."""
    return (sum_log_cosh_error / n_obs).squeeze()


def log_cosh_error(preds: Array, target: Array) -> Array:
    """Log-cosh error (reference ``log_cosh.py:58-85``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.log_cosh import log_cosh_error
        >>> print(round(float(log_cosh_error(preds, target)), 4))
        0.1685
    """
    sum_log_cosh_error, n_obs = _log_cosh_error_update(
        preds, target, num_outputs=1 if preds.ndim == 1 else preds.shape[-1]
    )
    return _log_cosh_error_compute(sum_log_cosh_error, n_obs)
