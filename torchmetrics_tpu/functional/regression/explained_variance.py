"""Explained variance (reference ``functional/regression/explained_variance.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array

ALLOWED_MULTIOUTPUT = ("raw_values", "uniform_average", "variance_weighted")


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    """n, Σerr, Σerr², Σy, Σy² (reference ``explained_variance.py:25-43``)."""
    _check_same_shape(preds, target)
    n_obs = preds.shape[0]
    diff = target - preds
    sum_error = jnp.sum(diff, axis=0)
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    sum_target = jnp.sum(target, axis=0)
    sum_squared_target = jnp.sum(target * target, axis=0)
    return n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    n_obs: Union[int, Array],
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    """Reference ``explained_variance.py:45-97``."""
    diff_avg = sum_error / n_obs
    numerator = sum_squared_error / n_obs - (diff_avg * diff_avg)
    target_avg = sum_target / n_obs
    denominator = sum_squared_target / n_obs - (target_avg * target_avg)

    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    valid_score = nonzero_numerator & nonzero_denominator
    output_scores = jnp.ones_like(jnp.atleast_1d(diff_avg) * 1.0)
    output_scores = jnp.where(
        valid_score, 1.0 - (numerator / jnp.where(valid_score, denominator, 1.0)), output_scores
    )
    output_scores = jnp.where(nonzero_numerator & ~nonzero_denominator, 0.0, output_scores)

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(f"Argument `multioutput` must be one of {ALLOWED_MULTIOUTPUT}, but got {multioutput}")


def explained_variance(preds: Array, target: Array, multioutput: str = "uniform_average") -> Array:
    """Explained variance (reference ``explained_variance.py:100-137``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.explained_variance import explained_variance
        >>> print(round(float(explained_variance(preds, target)), 4))
        0.9572
    """
    if multioutput not in ALLOWED_MULTIOUTPUT:
        raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {ALLOWED_MULTIOUTPUT}")
    n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
    return _explained_variance_compute(
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target, multioutput
    )
