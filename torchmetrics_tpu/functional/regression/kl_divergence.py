"""KL divergence (reference ``functional/regression/kl_divergence.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.compute import _safe_xlogy

Array = jax.Array


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, int]:
    """Per-row KL measures + count (reference ``kl_divergence.py:23-45``)."""
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")
    total = p.shape[0]
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / p.sum(axis=-1, keepdims=True)
        q = q / q.sum(axis=-1, keepdims=True)
        measures = _safe_xlogy(p, p / q).sum(axis=-1)
    return measures, total


def _kld_compute(measures: Array, total: Union[int, Array], reduction: str = "mean") -> Array:
    """Reference ``kl_divergence.py:48-77``."""
    if reduction == "sum":
        return measures.sum()
    if reduction == "mean":
        return measures.sum() / total
    if reduction is None or reduction == "none":
        return measures
    return measures / total


def kl_divergence(p: Array, q: Array, log_prob: bool = False, reduction: str = "mean") -> Array:
    """KL(P||Q) (reference ``kl_divergence.py:80-112``)."""
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, total, reduction)
