"""Symmetric MAPE (reference ``functional/regression/symmetric_mape.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    """Reference ``symmetric_mape.py:22-41``."""
    _check_same_shape(preds, target)
    abs_diff = jnp.abs(preds - target)
    arr = jnp.clip(jnp.abs(target) + jnp.abs(preds), epsilon, None)
    sum_abs_per_error = jnp.sum(2 * abs_diff / arr)
    return sum_abs_per_error, target.size


def _symmetric_mean_absolute_percentage_error_compute(
    sum_abs_per_error: Array, num_obs: Union[int, Array]
) -> Array:
    """Reference ``symmetric_mape.py:44-58``."""
    return sum_abs_per_error / num_obs


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """SMAPE (reference ``symmetric_mape.py:61-85``)."""
    sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
