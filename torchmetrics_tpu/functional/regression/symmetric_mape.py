"""Symmetric MAPE (reference ``functional/regression/symmetric_mape.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    """Reference ``symmetric_mape.py:22-41``."""
    _check_same_shape(preds, target)
    abs_diff = jnp.abs(preds - target)
    arr = jnp.clip(jnp.abs(target) + jnp.abs(preds), epsilon, None)
    sum_abs_per_error = jnp.sum(2 * abs_diff / arr)
    return sum_abs_per_error, target.size


def _symmetric_mean_absolute_percentage_error_compute(
    sum_abs_per_error: Array, num_obs: Union[int, Array]
) -> Array:
    """Reference ``symmetric_mape.py:44-58``."""
    return sum_abs_per_error / num_obs


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """SMAPE (reference ``symmetric_mape.py:61-85``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.symmetric_mape import symmetric_mean_absolute_percentage_error
        >>> print(round(float(symmetric_mean_absolute_percentage_error(preds, target)), 4))
        0.2455
    """
    sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
