"""Cosine similarity (reference ``functional/regression/cosine_similarity.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate 2D inputs and pass through (reference ``cosine_similarity.py:22-36``)."""
    _check_same_shape(preds, target)
    preds = preds.astype(jnp.float32)
    target = target.astype(jnp.float32)
    return preds, target


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Row-wise cosine + reduction (reference ``cosine_similarity.py:39-60``)."""
    dot_product = (preds * target).sum(axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    reduction_mapping = {
        "sum": jnp.sum,
        "mean": jnp.mean,
        "none": lambda x: x,
        None: lambda x: x,
    }
    if reduction not in reduction_mapping:
        raise ValueError(f"Expected reduction to be one of {list(reduction_mapping)} but got {reduction}")
    return reduction_mapping[reduction](similarity)


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Cosine similarity (reference ``cosine_similarity.py:63-92``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        >>> target = jnp.asarray([[1.0, 2.5], [2.5, 4.0], [5.5, 6.5]])
        >>> from torchmetrics_tpu.functional.regression.cosine_similarity import cosine_similarity
        >>> print(round(float(cosine_similarity(preds, target)), 4))
        2.9929
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
