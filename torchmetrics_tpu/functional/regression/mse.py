"""Mean squared error (reference ``functional/regression/mse.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_squared_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, int]:
    """Sum of squared errors + count (reference ``mse.py:22-41``)."""
    _check_same_shape(preds, target)
    if num_outputs == 1:
        preds = preds.reshape(-1)
        target = target.reshape(-1)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    return sum_squared_error, target.shape[0]


def _mean_squared_error_compute(sum_squared_error: Array, n_obs: Union[int, Array], squared: bool = True) -> Array:
    """Reference ``mse.py:44-60``."""
    return sum_squared_error / n_obs if squared else jnp.sqrt(sum_squared_error / n_obs)


def mean_squared_error(preds: Array, target: Array, squared: bool = True, num_outputs: int = 1) -> Array:
    """MSE (or RMSE with ``squared=False``); reference ``mse.py:63-88``.

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import mean_squared_error
        >>> x = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> y = jnp.asarray([0.0, 1.0, 2.0, 2.0])
        >>> print(float(mean_squared_error(x, y)))
        0.25
    """
    sum_squared_error, n_obs = _mean_squared_error_update(preds, target, num_outputs)
    return _mean_squared_error_compute(sum_squared_error, n_obs, squared=squared)
