"""Weighted MAPE (reference ``functional/regression/wmape.py``)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Σ|err| and Σ|target| (reference ``wmape.py:22-36``)."""
    _check_same_shape(preds, target)
    sum_abs_error = jnp.sum(jnp.abs((preds - target).flatten()))
    sum_scale = jnp.sum(jnp.abs(target.flatten()))
    return sum_abs_error, sum_scale


def _weighted_mean_absolute_percentage_error_compute(
    sum_abs_error: Array, sum_scale: Array, epsilon: float = 1.17e-06
) -> Array:
    """Reference ``wmape.py:39-50``."""
    return sum_abs_error / jnp.clip(sum_scale, epsilon, None)


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """WMAPE (reference ``wmape.py:53-79``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.wmape import weighted_mean_absolute_percentage_error
        >>> print(round(float(weighted_mean_absolute_percentage_error(preds, target)), 4))
        0.16
    """
    sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
    return _weighted_mean_absolute_percentage_error_compute(sum_abs_error, sum_scale)
