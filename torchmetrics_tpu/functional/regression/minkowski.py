"""Minkowski distance (reference ``functional/regression/minkowski.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.exceptions import TorchMetricsUserError

Array = jax.Array


def _minkowski_distance_update(preds: Array, targets: Array, p: float) -> Array:
    """Σ|err|^p (reference ``minkowski.py:21-37``)."""
    _check_same_shape(preds, targets)
    if not (isinstance(p, (float, int)) and p >= 1):
        raise TorchMetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
    difference = jnp.abs(preds - targets)
    return jnp.sum(jnp.power(difference, p))


def _minkowski_distance_compute(distance: Array, p: float) -> Array:
    """Reference ``minkowski.py:40-52``."""
    return jnp.power(distance, 1.0 / p)


def minkowski_distance(preds: Array, targets: Array, p: float) -> Array:
    """Minkowski distance (reference ``minkowski.py:55-80``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.minkowski import minkowski_distance
        >>> print(round(float(minkowski_distance(preds, target, p=3.0)), 4))
        1.0772
    """
    minkowski_dist_sum = _minkowski_distance_update(preds, targets, p)
    return _minkowski_distance_compute(minkowski_dist_sum, p)
