"""Tweedie deviance score (reference ``functional/regression/tweedie_deviance.py``)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.compute import _safe_xlogy

Array = jax.Array


def _tweedie_tensor_validation(preds: Array, targets: Array, power: float) -> None:
    """Host-side domain checks (reference ``tweedie_deviance.py:37-76``).

    Skipped automatically under ``jax.jit`` tracing — value checks need concrete data,
    and the update itself must stay jit-compilable (SURVEY §7 thesis 4).
    """
    if isinstance(preds, jax.core.Tracer) or isinstance(targets, jax.core.Tracer):
        return
    if power == 1 and (bool(np.any(np.asarray(preds) <= 0)) or bool(np.any(np.asarray(targets) < 0))):
        raise ValueError(f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative.")
    if power == 2 and (bool(np.any(np.asarray(preds) <= 0)) or bool(np.any(np.asarray(targets) <= 0))):
        raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
    if power < 0 and bool(np.any(np.asarray(preds) <= 0)):
        raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
    if 1 < power < 2 and (bool(np.any(np.asarray(preds) <= 0)) or bool(np.any(np.asarray(targets) < 0))):
        raise ValueError(f"For power={power}, 'targets' has to be strictly positive and 'preds' cannot be negative.")
    if power >= 2 and power != 2 and (bool(np.any(np.asarray(preds) <= 0)) or bool(np.any(np.asarray(targets) <= 0))):
        raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    """Σ deviance + count for the given power (reference ``tweedie_deviance.py:23-83``).

    Pure tensor math — all data-dependent domain checks live in
    ``_tweedie_tensor_validation`` so this lowers to one XLA graph.
    """
    _check_same_shape(preds, targets)
    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    _tweedie_tensor_validation(preds, targets, power)

    if power == 0:
        deviance_score = (targets - preds) ** 2
    elif power == 1:
        deviance_score = 2 * (_safe_xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:
        deviance_score = 2 * (jnp.log(preds / targets) + (targets / preds) - 1)
    else:
        term_1 = jnp.power(jnp.maximum(targets, 0.0), 2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * jnp.power(preds, 1 - power) / (1 - power)
        term_3 = jnp.power(preds, 2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    return jnp.sum(deviance_score), jnp.asarray(deviance_score.size, dtype=jnp.int32)


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    """Reference ``tweedie_deviance.py:86-100``."""
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Tweedie deviance (reference ``tweedie_deviance.py:103-142``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.tweedie_deviance import tweedie_deviance_score
        >>> print(round(float(tweedie_deviance_score(preds, target, power=1.5)), 4))
        0.112
    """
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, power)
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)
