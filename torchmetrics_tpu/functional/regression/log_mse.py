"""Mean squared log error (reference ``functional/regression/log_mse.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Σ(log1p(pred) − log1p(target))² + count (reference ``log_mse.py:22-36``)."""
    _check_same_shape(preds, target)
    sum_squared_log_error = jnp.sum((jnp.log1p(preds) - jnp.log1p(target)) ** 2)
    return sum_squared_log_error, target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, n_obs: Union[int, Array]) -> Array:
    """Reference ``log_mse.py:39-53``."""
    return sum_squared_log_error / n_obs


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """MSLE (reference ``log_mse.py:56-79``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.log_mse import mean_squared_log_error
        >>> print(round(float(mean_squared_log_error(preds, target)), 4))
        0.0286
    """
    sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
    return _mean_squared_log_error_compute(sum_squared_log_error, n_obs)
