"""Spearman rank correlation (reference ``functional/regression/spearman.py``).

Tie-aware average ranks computed with a fully vectorized sort/searchsorted formulation
(the reference loops over repeated values, ``spearman.py:23-53``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs
from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _rank_data(data: Array) -> Array:
    """Average rank of each element, ties share the mean rank (reference ``spearman.py:36-53``).

    (count of values < x) + (count of values <= x) + 1, halved — a closed form for the
    average of the positions a tied group occupies. Branch-free and O(n log n).
    """
    sorted_data = jnp.sort(data)
    lower = jnp.searchsorted(sorted_data, data, side="left")
    upper = jnp.searchsorted(sorted_data, data, side="right")
    return (lower + upper + 1) / 2.0


def _spearman_corrcoef_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    """Validate + pass through raw values (list states; reference ``spearman.py:56-73``)."""
    if not (jnp.issubdtype(preds.dtype, jnp.floating) and jnp.issubdtype(target.dtype, jnp.floating)):
        raise TypeError(
            "Expected `preds` and `target` both to be floating point tensors, but got"
            f" {preds.dtype} and {target.dtype}"
        )
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    return preds, target


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    """Rank then Pearson on ranks (reference ``spearman.py:76-96``)."""
    if preds.ndim == 1:
        preds = _rank_data(preds)
        target = _rank_data(target)
    else:
        preds = jnp.stack([_rank_data(preds[:, i]) for i in range(preds.shape[1])]).T
        target = jnp.stack([_rank_data(target[:, i]) for i in range(target.shape[1])]).T
    preds_diff = preds - preds.mean(0)
    target_diff = target - target.mean(0)
    cov = (preds_diff * target_diff).mean(0)
    preds_std = jnp.sqrt((preds_diff * preds_diff).mean(0))
    target_std = jnp.sqrt((target_diff * target_diff).mean(0))
    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Spearman ρ (reference ``spearman.py:99-125``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.spearman import spearman_corrcoef
        >>> print(round(float(spearman_corrcoef(preds, target)), 4))
        1.0
    """
    preds, target = _spearman_corrcoef_update(
        preds, target, num_outputs=1 if preds.ndim == 1 else preds.shape[-1]
    )
    return _spearman_corrcoef_compute(preds, target)
