"""Kendall rank correlation (reference ``functional/regression/kendall.py``).

TPU-first redesign: the reference counts concordant/discordant pairs with a Python
loop over elements (``kendall.py:61-87``) and computes tie statistics with per-column
``bincount`` loops (``kendall.py:100-113``). Here everything is one O(n²) masked
sign-product reduction over the pairwise difference matrix, vmapped over outputs —
branch-free, static shapes, single XLA graph. Tie-group statistics Σt(t−1)(t−2) and
Σt(t−1)(2t+5) come from the pairwise equality matrix: every element of a tie group of
size t has row-count c_i = t, so Σ_groups f(t) = Σ_i f(c_i)/c_i without any grouping.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.scipy.stats import norm

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs
from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.enums import EnumStr

Array = jax.Array


class _MetricVariant(EnumStr):
    """Tau variant selector (reference ``kendall.py:26-34``)."""

    A = "a"
    B = "b"
    C = "c"

    @staticmethod
    def _name() -> str:
        return "variant"


class _TestAlternative(EnumStr):
    """Hypothesis-test alternative (reference ``kendall.py:37-46``)."""

    TWO_SIDED = "two-sided"
    LESS = "less"
    GREATER = "greater"

    @staticmethod
    def _name() -> str:
        return "alternative"


_PAIR_BLOCK = 512


def _kendall_stats_1d(x: Array, y: Array) -> Tuple[Array, ...]:
    """All pairwise statistics for a single (n,) pair, blocked to O(block·n) memory.

    A ``lax.scan`` over row blocks compares each block against all n columns, so the
    O(n²) pair comparisons never materialize an (n,n) matrix (peak memory is
    ``_PAIR_BLOCK × n`` — a 50k-sample stream peaks at ~100 MB instead of ~10 GB).
    Returns (concordant, discordant, ties_x_pairs, ties_y_pairs, x_p1, x_p2, y_p1,
    y_p2, n_unique_x, n_unique_y); every value is a 0-d array so the whole thing
    vmaps over the outputs axis.
    """
    n = x.shape[0]
    block = min(_PAIR_BLOCK, n)
    pad = (-n) % block
    # pad with +inf so padded entries never tie with real data; masked out anyway
    xp = jnp.concatenate([x, jnp.full((pad,), jnp.inf, dtype=x.dtype)])
    yp = jnp.concatenate([y, jnp.full((pad,), jnp.inf, dtype=y.dtype)])
    idx = jnp.arange(n + pad)
    valid = idx < n
    # Accumulate in the widest float the backend allows (f64 under x64, f32 on TPU).
    acc_dtype = jnp.result_type(jnp.float32, jnp.float64)

    row_starts = jnp.arange(0, n + pad, block)

    def body(carry, start):
        rows = start + jnp.arange(block)
        xi = xp[rows]
        yi = yp[rows]
        vi = valid[rows]
        dx = xi[:, None] - xp[None, :]
        dy = yi[:, None] - yp[None, :]
        pair_mask = vi[:, None] & valid[None, :] & (rows[:, None] < idx[None, :])
        prod = jnp.sign(dx) * jnp.sign(dy)
        # accumulate counts in acc_dtype — int32 would overflow at n(n-1)/2 pairs
        # (~65.5k samples); f64 is exact far beyond any realistic stream
        con = jnp.sum((prod > 0) & pair_mask, dtype=acc_dtype)
        dis = jnp.sum((prod < 0) & pair_mask, dtype=acc_dtype)
        # c_i = size of the tie group row i belongs to (count over all valid columns)
        cx = jnp.sum((dx == 0) & valid[None, :], axis=1).astype(acc_dtype)
        cy = jnp.sum((dy == 0) & valid[None, :], axis=1).astype(acc_dtype)
        vrow = vi.astype(acc_dtype)
        sums = jnp.stack([
            jnp.sum(vrow * (cx - 1)) / 2,  # Σ_groups t(t-1)/2 (per-row halves)
            jnp.sum(vrow * (cy - 1)) / 2,
            jnp.sum(vrow * (cx - 1) * (cx - 2)),  # Σ_groups t(t-1)(t-2)
            jnp.sum(vrow * (cy - 1) * (cy - 2)),
            jnp.sum(vrow * (cx - 1) * (2 * cx + 5)),  # Σ_groups t(t-1)(2t+5)
            jnp.sum(vrow * (cy - 1) * (2 * cy + 5)),
            jnp.sum(vrow / jnp.maximum(cx, 1.0)),  # Σ 1/t = #unique
            jnp.sum(vrow / jnp.maximum(cy, 1.0)),
        ])
        c_con, c_dis, c_sums = carry
        return (c_con + con, c_dis + dis, c_sums + sums), None

    init = (jnp.zeros((), dtype=acc_dtype), jnp.zeros((), dtype=acc_dtype), jnp.zeros(8, dtype=acc_dtype))
    (concordant, discordant, sums), _ = jax.lax.scan(body, init, row_starts)
    ties_x, ties_y, x_p1, y_p1, x_p2, y_p2, n_unique_x, n_unique_y = sums
    return concordant, discordant, ties_x, ties_y, x_p1, x_p2, y_p1, y_p2, n_unique_x, n_unique_y


def _calculate_tau(
    stats: Tuple[Array, ...],
    n_total: Array,
    variant: _MetricVariant,
) -> Array:
    """Tau from pairwise statistics (formulas per reference ``kendall.py:152-175``)."""
    con, dis, ties_x, ties_y, _, _, _, _, nux, nuy = stats
    con_min_dis = (con - dis).astype(ties_x.dtype)
    if variant == _MetricVariant.A:
        return con_min_dis / (con + dis)
    if variant == _MetricVariant.B:
        n0 = n_total * (n_total - 1) / 2
        return con_min_dis / jnp.sqrt((n0 - ties_x) * (n0 - ties_y))
    min_classes = jnp.minimum(nux, nuy)
    return 2 * con_min_dis / ((min_classes - 1) / min_classes * n_total**2)


def _calculate_p_value(
    stats: Tuple[Array, ...],
    n_total: Array,
    variant: _MetricVariant,
    alternative: Optional[_TestAlternative],
) -> Array:
    """Asymptotic-normal p-value with tie correction (reference ``kendall.py:193-224``)."""
    con, dis, ties_x, ties_y, x_p1, x_p2, y_p1, y_p2, _, _ = stats
    con_min_dis = (con - dis).astype(ties_x.dtype)
    base = n_total * (n_total - 1) * (2 * n_total + 5)
    if variant == _MetricVariant.A:
        t_value = 3 * con_min_dis / jnp.sqrt(base / 2)
    else:
        m = n_total * (n_total - 1)
        denom = (base - x_p2 - y_p2) / 18
        denom = denom + (2 * ties_x * ties_y) / m
        denom = denom + x_p1 * y_p1 / (9 * m * (n_total - 2))
        t_value = con_min_dis / jnp.sqrt(denom)

    if alternative == _TestAlternative.TWO_SIDED:
        t_value = jnp.abs(t_value)
    if alternative in (_TestAlternative.TWO_SIDED, _TestAlternative.GREATER):
        t_value = -t_value
    p_value = norm.cdf(t_value)
    if alternative == _TestAlternative.TWO_SIDED:
        p_value = p_value * 2
    return p_value


def _kendall_corrcoef_update(
    preds: Array,
    target: Array,
    num_outputs: int = 1,
) -> Tuple[Array, Array]:
    """Validate and shape batch for the (cat) list states (reference ``kendall.py:227-258``)."""
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    if num_outputs == 1 and preds.ndim == 1:
        preds = preds[:, None]
        target = target[:, None]
    return preds, target


def _kendall_corrcoef_compute(
    preds: Array,
    target: Array,
    variant: _MetricVariant,
    alternative: Optional[_TestAlternative] = None,
) -> Tuple[Array, Optional[Array]]:
    """Tau (+ optional p-value) over the concatenated data (reference ``kendall.py:261-291``)."""
    n_total = jnp.asarray(preds.shape[0], dtype=jnp.result_type(jnp.float32, jnp.float64))
    stats = jax.vmap(_kendall_stats_1d, in_axes=1, out_axes=0)(preds, target)
    tau = _calculate_tau(stats, n_total, variant)
    p_value = _calculate_p_value(stats, n_total, variant, alternative) if alternative is not None else None
    tau = jnp.clip(tau.squeeze(), -1.0, 1.0)
    if p_value is not None:
        p_value = p_value.squeeze()
    return tau, p_value


def kendall_rank_corrcoef(
    preds: Array,
    target: Array,
    variant: str = "b",
    t_test: bool = False,
    alternative: Optional[str] = "two-sided",
) -> Union[Array, Tuple[Array, Array]]:
    """Kendall's tau (reference ``kendall.py:294-355``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.kendall import kendall_rank_corrcoef
        >>> print(round(float(kendall_rank_corrcoef(preds, target)), 4))
        1.0
    """
    if not isinstance(t_test, bool):
        raise ValueError(f"Argument `t_test` is expected to be of a type `bool`, but got {type(t_test)}.")
    if t_test and alternative is None:
        raise ValueError("Argument `alternative` is required if `t_test=True` but got `None`.")
    _variant = _MetricVariant.from_str(str(variant))
    _alternative = _TestAlternative.from_str(str(alternative)) if t_test else None

    preds2, target2 = _kendall_corrcoef_update(
        preds, target, num_outputs=1 if preds.ndim == 1 else preds.shape[-1]
    )
    tau, p_value = _kendall_corrcoef_compute(preds2, target2, _variant, _alternative)
    if p_value is not None:
        return tau, p_value
    return tau
