"""Mean absolute error (reference ``functional/regression/mae.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Sum of absolute errors + count (reference ``mae.py:22-34``)."""
    _check_same_shape(preds, target)
    preds = preds if jnp.issubdtype(preds.dtype, jnp.floating) else preds.astype(jnp.float32)
    target = target if jnp.issubdtype(target.dtype, jnp.floating) else target.astype(jnp.float32)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, target.size


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs: Union[int, Array]) -> Array:
    """Reference ``mae.py:37-50``."""
    return sum_abs_error / n_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """MAE (reference ``mae.py:53-72``)."""
    sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, n_obs)
