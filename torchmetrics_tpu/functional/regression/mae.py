"""Mean absolute error (reference ``functional/regression/mae.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Sum of absolute errors + count (reference ``mae.py:22-34``)."""
    _check_same_shape(preds, target)
    preds = preds if jnp.issubdtype(preds.dtype, jnp.floating) else preds.astype(jnp.float32)
    target = target if jnp.issubdtype(target.dtype, jnp.floating) else target.astype(jnp.float32)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, target.size


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs: Union[int, Array]) -> Array:
    """Reference ``mae.py:37-50``."""
    return sum_abs_error / n_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """MAE (reference ``mae.py:53-72``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.mae import mean_absolute_error
        >>> print(round(float(mean_absolute_error(preds, target)), 4))
        0.5
    """
    sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, n_obs)
