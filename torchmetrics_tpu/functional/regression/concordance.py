"""Concordance correlation coefficient (reference ``functional/regression/concordance.py``).

Reuses the Pearson streaming-moment state; CCC = 2ρσ_xσ_y / (σ_x² + σ_y² + (μ_x−μ_y)²).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.pearson import (
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)

Array = jax.Array


def _concordance_corrcoef_compute(
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    nb: Array,
) -> Array:
    """CCC from accumulated moments (reference ``concordance.py:21-31``).

    Uses sample variances (÷(n−1)); the reference reaches the same numbers via an
    in-place ``/=`` inside ``_pearson_corrcoef_compute`` mutating its caller's tensors.
    """
    pearson = _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    return 2.0 * pearson * jnp.sqrt(var_x) * jnp.sqrt(var_y) / (var_x + var_y + (mean_x - mean_y) ** 2)


def concordance_corrcoef(preds: Array, target: Array) -> Array:
    """Concordance correlation (reference ``concordance.py:34-69``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.concordance import concordance_corrcoef
        >>> print(round(float(concordance_corrcoef(preds, target)), 4))
        0.9777
    """
    d = preds.shape[1] if preds.ndim == 2 else 1
    _temp = jnp.zeros(d, dtype=jnp.result_type(preds, jnp.float32)).squeeze()
    mean_x, mean_y, var_x = _temp, _temp, _temp
    var_y, corr_xy, nb = _temp, _temp, _temp
    mean_x, mean_y, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, mean_x, mean_y, var_x, var_y, corr_xy, nb,
        num_outputs=1 if preds.ndim == 1 else preds.shape[-1],
    )
    return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, nb)
