"""Mean absolute percentage error (reference ``functional/regression/mape.py``)."""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    """Sum of |err|/|target| + count (reference ``mape.py:22-40``)."""
    _check_same_shape(preds, target)
    abs_diff = jnp.abs(preds - target)
    abs_per_error = abs_diff / jnp.clip(jnp.abs(target), epsilon, None)
    sum_abs_per_error = jnp.sum(abs_per_error)
    return sum_abs_per_error, target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Union[int, Array]) -> Array:
    """Reference ``mape.py:43-57``."""
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """MAPE (reference ``mape.py:60-86``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([2.5, 1.0, 2.0, 8.0])
        >>> target = jnp.asarray([3.0, 0.5, 2.0, 7.0])
        >>> from torchmetrics_tpu.functional.regression.mape import mean_absolute_percentage_error
        >>> print(round(float(mean_absolute_percentage_error(preds, target)), 4))
        0.3274
    """
    sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
