"""Pearson correlation via streaming moments (reference ``functional/regression/pearson.py``).

State = (mean_x, mean_y, var_x, var_y, corr_xy, n) updated incrementally; cross-chip
merge uses the pairwise-moment aggregation (the reference's ``_final_aggregation``,
``regression/pearson.py:28-70`` — promoted here into the functional module so both the
modular class and ``merge_state`` reuse it).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.regression.utils import _check_data_shape_to_num_outputs
from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
    num_outputs: int,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """One streaming-moment step (reference ``pearson.py:23-76``).

    Jit-safe: the reference's Python ``if n_prior > 0`` on a traced value becomes a
    ``jnp.where`` select between the running-moment increment and the numerically
    stable two-pass (centered-at-batch-mean) first-batch increment. Computing both
    branches costs a few elementwise ops; dropping the two-pass branch would suffer
    catastrophic cancellation in f32 for large-mean data (Σ(x−m)·x ≈ Σx² − …).
    """
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    cond = n_prior > 0
    n_obs = preds.shape[0]
    n_total = n_prior + n_obs
    mx_batch = preds.mean(0)
    my_batch = target.mean(0)
    mx_new = jnp.where(cond, (n_prior * mean_x + preds.sum(0)) / n_total, mx_batch)
    my_new = jnp.where(cond, (n_prior * mean_y + target.sum(0)) / n_total, my_batch)
    var_x = var_x + jnp.where(
        cond,
        ((preds - mx_new) * (preds - mean_x)).sum(0),
        ((preds - mx_batch) ** 2).sum(0),
    )
    var_y = var_y + jnp.where(
        cond,
        ((target - my_new) * (target - mean_y)).sum(0),
        ((target - my_batch) ** 2).sum(0),
    )
    corr_xy = corr_xy + jnp.where(
        cond,
        ((preds - mx_new) * (target - mean_y)).sum(0),
        ((preds - mx_batch) * (target - my_batch)).sum(0),
    )
    return mx_new, my_new, var_x, var_y, corr_xy, n_total


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    """Final correlation (reference ``pearson.py:78-101``)."""
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = (corr_xy / jnp.sqrt(var_x * var_y)).squeeze()
    return jnp.clip(corrcoef, -1.0, 1.0)


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Pairwise-moment merge of per-device statistics (reference ``regression/pearson.py:28-70``)."""
    if len(means_x) == 1:
        return means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, len(means_x)):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        mean_x = (n1 * mx1 + n2 * mx2) / nb
        mean_y = (n1 * my1 + n2 * my2) / nb

        element_x1 = (n1 + 1) * mean_x - n1 * mx1
        vx1 = vx1 + (element_x1 - mx1) * (element_x1 - mean_x) - (element_x1 - mean_x) ** 2
        element_x2 = (n2 + 1) * mean_x - n2 * mx2
        vx2 = vx2 + (element_x2 - mx2) * (element_x2 - mean_x) - (element_x2 - mean_x) ** 2
        var_x = vx1 + vx2

        element_y1 = (n1 + 1) * mean_y - n1 * my1
        vy1 = vy1 + (element_y1 - my1) * (element_y1 - mean_y) - (element_y1 - mean_y) ** 2
        element_y2 = (n2 + 1) * mean_y - n2 * my2
        vy2 = vy2 + (element_y2 - my2) * (element_y2 - mean_y) - (element_y2 - mean_y) ** 2
        var_y = vy1 + vy2

        cxy1 = cxy1 + (element_x1 - mx1) * (element_y1 - mean_y) - (element_x1 - mean_x) * (element_y1 - mean_y)
        cxy2 = cxy2 + (element_x2 - mx2) * (element_y2 - mean_y) - (element_x2 - mean_x) * (element_y2 - mean_y)
        corr_xy = cxy1 + cxy2

        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return mean_x, mean_y, var_x, var_y, corr_xy, nb


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Pearson r (reference ``pearson.py:104-130``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import pearson_corrcoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> print(round(float(pearson_corrcoef(preds, target)), 4))
        0.9849
    """
    d = preds.shape[1] if preds.ndim == 2 else 1
    _temp = jnp.zeros(d).squeeze()
    mean_x, mean_y, var_x = _temp, _temp, _temp
    var_y, corr_xy, nb = _temp, _temp, _temp
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, mean_x, mean_y, var_x, var_y, corr_xy, nb,
        num_outputs=1 if preds.ndim == 1 else preds.shape[-1],
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
