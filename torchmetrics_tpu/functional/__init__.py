"""Stateless functional metric API (reference ``src/torchmetrics/functional/__init__.py``).

Flat re-export of all domain functionals so ``from torchmetrics_tpu.functional import
accuracy`` works like the reference's ``torchmetrics.functional`` namespace.
"""

from torchmetrics_tpu.functional.audio import *  # noqa: F401,F403
from torchmetrics_tpu.functional.audio import __all__ as _audio_all
from torchmetrics_tpu.functional.classification import *  # noqa: F401,F403
from torchmetrics_tpu.functional.classification import __all__ as _classification_all
from torchmetrics_tpu.functional.detection import *  # noqa: F401,F403
from torchmetrics_tpu.functional.detection import __all__ as _detection_all
from torchmetrics_tpu.functional.image import *  # noqa: F401,F403
from torchmetrics_tpu.functional.image import __all__ as _image_all
from torchmetrics_tpu.functional.multimodal import *  # noqa: F401,F403
from torchmetrics_tpu.functional.multimodal import __all__ as _multimodal_all
from torchmetrics_tpu.functional.nominal import *  # noqa: F401,F403
from torchmetrics_tpu.functional.nominal import __all__ as _nominal_all
from torchmetrics_tpu.functional.pairwise import *  # noqa: F401,F403
from torchmetrics_tpu.functional.pairwise import __all__ as _pairwise_all
from torchmetrics_tpu.functional.regression import *  # noqa: F401,F403
from torchmetrics_tpu.functional.regression import __all__ as _regression_all
from torchmetrics_tpu.functional.retrieval import *  # noqa: F401,F403
from torchmetrics_tpu.functional.retrieval import __all__ as _retrieval_all
from torchmetrics_tpu.functional.text import *  # noqa: F401,F403
from torchmetrics_tpu.functional.text import __all__ as _text_all

__all__ = (
    list(_audio_all)
    + list(_classification_all)
    + list(_detection_all)
    + list(_multimodal_all)
    + list(_nominal_all)
    + list(_pairwise_all)
    + list(_regression_all)
    + list(_retrieval_all)
    + list(_image_all)
    + list(_text_all)
)
