"""Expected calibration error (reference ``functional/classification/calibration_error.py``).

States are the raw (confidence, accuracy) streams (cat lists in the modular class);
binning happens in compute via one scatter-add per statistic — deterministic on XLA.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
)
from torchmetrics_tpu.functional.classification.stat_scores import _is_floating
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


def _binning_bucketize(
    confidences: Array, accuracies: Array, bin_boundaries: Array
) -> Tuple[Array, Array, Array]:
    """Per-bin accuracy/confidence/proportion via scatter-adds (reference ``calibration_error.py:29-58``)."""
    accuracies = accuracies.astype(confidences.dtype)
    n_bins = bin_boundaries.shape[0]
    indices = jnp.searchsorted(bin_boundaries, confidences, side="right") - 1
    count_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(1.0)
    conf_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(confidences)
    acc_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(accuracies)
    conf_bin = jnp.nan_to_num(conf_bin / count_bin)
    acc_bin = jnp.nan_to_num(acc_bin / count_bin)
    # zero observed samples: every bin proportion is the documented zero, not 0/0
    prop_bin = _safe_divide(count_bin, count_bin.sum())
    return acc_bin, conf_bin, prop_bin


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Union[Array, int],
    norm: str = "l1",
    debias: bool = False,
) -> Array:
    """Calibration error under l1/l2/max norms (reference ``calibration_error.py:61-107``)."""
    if isinstance(bin_boundaries, int):
        bin_boundaries = jnp.linspace(0, 1, bin_boundaries + 1, dtype=jnp.float32)
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")

    acc_bin, conf_bin, prop_bin = _binning_bucketize(confidences, accuracies, bin_boundaries)
    if norm == "l1":
        return jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    if norm == "max":
        return jnp.max(jnp.abs(acc_bin - conf_bin))
    ce = jnp.sum((acc_bin - conf_bin) ** 2 * prop_bin)
    if debias:
        debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (prop_bin * confidences.shape[0] - 1)
        ce = ce + jnp.sum(jnp.nan_to_num(debias_bins))
    return jnp.where(ce > 0, jnp.sqrt(jnp.maximum(ce, 0.0)), 0.0)


def _binary_calibration_error_arg_validation(
    n_bins: int,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``calibration_error.py:110-121``."""
    if not isinstance(n_bins, int) or n_bins < 1:
        raise ValueError(f"Expected argument `n_bins` to be an integer larger than 0, but got {n_bins}")
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Expected argument `norm` to be one of ('l1', 'l2', 'max'), but got {norm}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_calibration_error_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    """Reference ``calibration_error.py:124-131``."""
    _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _binary_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """confidence = p(positive), accuracy = target (reference ``calibration_error.py:134-136``)."""
    return preds, target


def binary_calibration_error(
    preds: Array,
    target: Array,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """ECE for binary tasks (reference ``calibration_error.py:139-...``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.calibration_error import binary_calibration_error
        >>> print(round(float(binary_calibration_error(preds, target)), 4))
        0.3167
    """
    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _binary_calibration_error_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(
        preds, target, threshold=0.0, ignore_index=ignore_index, convert_to_labels=False
    )
    keep = np.asarray(target) >= 0
    if not keep.all():
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    confidences, accuracies = _binary_calibration_error_update(preds, target)
    return _ce_compute(confidences.astype(jnp.float32), accuracies.astype(jnp.float32), n_bins, norm)


def _multiclass_calibration_error_arg_validation(
    num_classes: int,
    n_bins: int,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``calibration_error.py:223-232``."""
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)


def _multiclass_calibration_error_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _multiclass_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Top-1 confidence + correctness (reference ``calibration_error.py:235-243``)."""
    if not bool(jnp.all((preds >= 0) & (preds <= 1))):
        preds = jax.nn.softmax(preds, axis=1)
    confidences = jnp.max(preds, axis=1)
    predictions = jnp.argmax(preds, axis=1)
    accuracies = (predictions == target).astype(jnp.float32)
    return confidences.astype(jnp.float32), accuracies


def multiclass_calibration_error(
    preds: Array,
    target: Array,
    num_classes: int,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label ECE for multiclass tasks (reference ``calibration_error.py:246-...``)."""
    if validate_args:
        _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        _multiclass_calibration_error_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index, convert_to_labels=False)
    keep = np.asarray(target) >= 0
    if not keep.all():
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    confidences, accuracies = _multiclass_calibration_error_update(preds, target)
    return _ce_compute(confidences, accuracies, n_bins, norm)


def calibration_error(
    preds: Array,
    target: Array,
    task: str,
    n_bins: int = 15,
    norm: str = "l1",
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing wrapper (reference legacy API)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_calibration_error(preds, target, n_bins, norm, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_calibration_error(preds, target, num_classes, n_bins, norm, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
