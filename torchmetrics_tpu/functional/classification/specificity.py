"""Specificity — binary / multiclass / multilabel (+ task router).

Capability parity: reference ``functional/classification/specificity.py`` (reduce ``:38-55``).
"""

from __future__ import annotations

from typing import Optional

import jax

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_pipeline,
)
from torchmetrics_tpu.utilities.compute import _adjust_weights_safe_divide, _safe_divide, _sum_axis
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _specificity_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reference ``specificity.py:38-55``: tn / (tn + fp)."""
    if average == "binary":
        return _safe_divide(tn, tn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tn = _sum_axis(tn, axis)
        fp = _sum_axis(fp, axis)
        return _safe_divide(tn, tn + fp)
    specificity_score = _safe_divide(tn, tn + fp)
    return _adjust_weights_safe_divide(specificity_score, average, multilabel, tp, fp, fn)


def binary_specificity(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Specificity for binary tasks (reference ``specificity.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.specificity import binary_specificity
        >>> print(round(float(binary_specificity(preds, target)), 4))
        0.6667
    """
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _specificity_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_specificity(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Specificity for multiclass tasks (reference ``specificity.py``)."""
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _specificity_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_specificity(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Specificity for multilabel tasks (reference ``specificity.py``)."""
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )
    return _specificity_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def specificity(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing specificity (reference ``specificity.py`` legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_specificity(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_specificity(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_specificity(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
