"""Hinge loss (reference ``functional/classification/hinge.py``).

Running sum + count states; fully branch-free updates (the reference's boolean-mask
writes become ``jnp.where`` selects).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
)
from torchmetrics_tpu.functional.classification.stat_scores import _is_floating
from torchmetrics_tpu.utilities.data import to_onehot
from torchmetrics_tpu.utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


def _hinge_loss_compute(measure: Array, total: Array) -> Array:
    """Reference ``hinge.py:30-31``."""
    return measure / total


def _binary_hinge_loss_arg_validation(squared: bool, ignore_index: Optional[int] = None) -> None:
    """Reference ``hinge.py:35-39``."""
    if not isinstance(squared, bool):
        raise ValueError(f"Expected argument `squared` to be an bool but got {squared}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_hinge_loss_tensor_validation(preds: Array, target: Array, ignore_index: Optional[int] = None) -> None:
    """Reference ``hinge.py:42-48``."""
    _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _binary_hinge_loss_update(preds: Array, target: Array, squared: bool) -> Tuple[Array, Array]:
    """Reference ``hinge.py:50-69`` — margin via where-select, jit-safe."""
    margin = jnp.where(target == 1, preds, -preds)
    measures = jnp.clip(1 - margin, 0, None)
    if squared:
        measures = measures**2
    total = jnp.asarray(target.shape[0], dtype=jnp.int32)
    return measures.sum(axis=0), total


def binary_hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    ignore_index: Optional[int] = None,
    validate_args: bool = False,
) -> Array:
    """Hinge loss for binary tasks (reference ``hinge.py:72-...``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.hinge import binary_hinge_loss
        >>> print(round(float(binary_hinge_loss(preds, target)), 4))
        0.8167
    """
    if validate_args:
        _binary_hinge_loss_arg_validation(squared, ignore_index)
        _binary_hinge_loss_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(
        preds, target, threshold=0.0, ignore_index=ignore_index, convert_to_labels=False
    )
    keep = np.asarray(target) >= 0
    if not keep.all():
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    measures, total = _binary_hinge_loss_update(preds, target, squared)
    return _hinge_loss_compute(measures, total)


def _multiclass_hinge_loss_arg_validation(
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``hinge.py:128-140``."""
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_hinge_loss_arg_validation(squared, ignore_index)
    if multiclass_mode not in ("crammer-singer", "one-vs-all"):
        raise ValueError(
            f"Expected argument `multiclass_mode` to be one of ('crammer-singer', 'one-vs-all') but got {multiclass_mode}"
        )


def _multiclass_hinge_loss_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _multiclass_hinge_loss_update(
    preds: Array,
    target: Array,
    squared: bool,
    multiclass_mode: str = "crammer-singer",
) -> Tuple[Array, Array]:
    """Reference ``hinge.py:149-175`` — masked max instead of boolean gather."""
    if not bool(jnp.all((preds >= 0) & (preds <= 1))):
        preds = jax.nn.softmax(preds, axis=1)
    target_oh = to_onehot(target, max(2, preds.shape[1])).astype(bool)
    if multiclass_mode == "crammer-singer":
        true_score = jnp.sum(jnp.where(target_oh, preds, 0.0), axis=1)
        best_other = jnp.max(jnp.where(target_oh, -jnp.inf, preds), axis=1)
        margin = true_score - best_other
    else:
        margin = jnp.where(target_oh, preds, -preds)
    measures = jnp.clip(1 - margin, 0, None)
    if squared:
        measures = measures**2
    total = jnp.asarray(target.shape[0], dtype=jnp.int32)
    return measures.sum(axis=0), total


def multiclass_hinge_loss(
    preds: Array,
    target: Array,
    num_classes: int,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = False,
) -> Array:
    """Hinge loss for multiclass tasks (reference ``hinge.py:185-...``)."""
    if validate_args:
        _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        _multiclass_hinge_loss_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index, convert_to_labels=False)
    keep = np.asarray(target) >= 0
    if not keep.all():
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    measures, total = _multiclass_hinge_loss_update(preds, target, squared, multiclass_mode)
    return _hinge_loss_compute(measures, total)


def hinge_loss(
    preds: Array,
    target: Array,
    task: str,
    num_classes: Optional[int] = None,
    squared: bool = False,
    multiclass_mode: str = "crammer-singer",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing wrapper (reference legacy API)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_hinge_loss(preds, target, squared, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_hinge_loss(preds, target, num_classes, squared, multiclass_mode, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
