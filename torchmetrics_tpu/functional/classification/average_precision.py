"""Average precision (reference ``functional/classification/average_precision.py``).

AP = Σ (R_n − R_{n−1}) · P_n over the PR curve — shares the PR-curve dual state.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.roc import _is_state_tensor
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.data import _bincount
from torchmetrics_tpu.utilities.enums import ClassificationTask
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _ap_from_curve(precision: Array, recall: Array) -> Array:
    return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])


def _reduce_average_precision(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reduce per-class APs (reference ``average_precision.py:43-67``)."""
    if isinstance(precision, (jnp.ndarray, jax.Array)) and not isinstance(precision, (list, tuple)):
        res = -jnp.sum((recall[:, 1:] - recall[:, :-1]) * precision[:, :-1], axis=1)
    else:
        res = jnp.stack([_ap_from_curve(p, r) for p, r in zip(precision, recall)])
    if average is None or average == "none":
        return res
    if bool(jnp.isnan(res).any()):
        rank_zero_warn(
            f"Average precision score for one or more classes was `nan`. Ignoring these classes in {average}-average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.sum(jnp.where(idx, res, 0.0)) / jnp.sum(idx)
    if average == "weighted" and weights is not None:
        w = jnp.where(idx, weights, 0.0)
        w = _safe_divide(w, jnp.sum(w))
        return jnp.sum(jnp.where(idx, res, 0.0) * w)
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Array:
    """Reference ``average_precision.py:70-77``."""
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds, pos_label)
    return _ap_from_curve(precision, recall)


def binary_average_precision(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AP for binary tasks (reference ``average_precision.py:80-148``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.average_precision import binary_average_precision
        >>> print(round(float(binary_average_precision(preds, target)), 4))
        0.9167
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_average_precision_compute(state, thresholds)


def _multiclass_average_precision_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``average_precision.py:151-160``."""
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None) but got {average}"
        )


def _multiclass_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    """Reference ``average_precision.py:163-175``."""
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if thresholds is None:
        target = state[1]
        keep = np.asarray(target) >= 0
        weights = _bincount(jnp.asarray(np.asarray(target)[keep]), minlength=num_classes).astype(jnp.float32)
    else:
        weights = state[0][:, 1, :].sum(-1).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multiclass_average_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AP for multiclass tasks (reference ``average_precision.py:178-267``)."""
    if validate_args:
        _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_average_precision_compute(state, num_classes, average, thresholds)


def _multilabel_average_precision_arg_validation(
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``average_precision.py:270-279``."""
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None) but got {average}"
        )


def _multilabel_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    """Reference ``average_precision.py:282-309``."""
    if average == "micro":
        if _is_state_tensor(state) and thresholds is not None:
            return _binary_average_precision_compute(state.sum(1), thresholds)
        preds = state[0].flatten()
        target = state[1].flatten()
        if ignore_index is not None:
            keep = np.asarray(target) != ignore_index
            preds = jnp.asarray(np.asarray(preds)[keep])
            target = jnp.asarray(np.asarray(target)[keep])
        return _binary_average_precision_compute((preds, target), thresholds)

    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if thresholds is None:
        weights = jnp.sum(state[1] == 1, axis=0).astype(jnp.float32)
    else:
        weights = state[0][:, 1, :].sum(-1).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multilabel_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AP for multilabel tasks (reference ``average_precision.py:312-...``)."""
    if validate_args:
        _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_average_precision_compute(state, num_labels, average, thresholds, ignore_index)


def average_precision(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-routing wrapper (reference legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
