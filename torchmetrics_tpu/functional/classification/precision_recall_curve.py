"""Precision-recall curves — the dual-state base of the curve family (ROC/AUROC/AP).

Capability parity: reference ``functional/classification/precision_recall_curve.py``
(``_binary_clf_curve:28``, binned updates ``:205-243``, compute ``:246-275``). Two modes:

* **binned** (``thresholds`` given) — state is a fixed ``(len_t, [C,] 2, 2)`` confusion
  tensor built by one weighted scatter-add; fully jit-safe and the TPU-preferred mode
  (static shapes, constant memory, single psum at sync).
* **exact** (``thresholds=None``) — sort-based curve over all scores, computed eagerly
  at epoch end (dynamic output length is inherent to the algorithm; the reference is
  also host-bound here).

``ignore_index`` in binned mode maps ignored samples to negative bins dropped by the
scatter — no boolean filtering, static shapes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.stat_scores import _is_floating
from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.data import _cumsum
from torchmetrics_tpu.utilities.enums import ClassificationTask
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _binary_clf_curve(
    preds: Array,
    target: Array,
    sample_weights: Optional[Sequence] = None,
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """fps/tps at every distinct prediction value (reference ``precision_recall_curve.py:28-79``).

    Eager (host-synced) — output length is data-dependent by construction.
    """
    if sample_weights is not None and not isinstance(sample_weights, (jnp.ndarray, jax.Array)):
        sample_weights = jnp.asarray(sample_weights, dtype=jnp.float32)
    if preds.ndim > target.ndim:
        preds = preds[:, 0]
    desc_score_indices = jnp.argsort(-preds)
    preds = preds[desc_score_indices]
    target = target[desc_score_indices]
    weight = sample_weights[desc_score_indices] if sample_weights is not None else 1.0

    distinct_value_indices = np.nonzero(np.asarray(preds[1:] - preds[:-1]))[0]
    threshold_idxs = jnp.asarray(np.concatenate([distinct_value_indices, [target.shape[0] - 1]]), dtype=jnp.int32)
    target = (target == pos_label).astype(jnp.int32)
    tps = _cumsum(target * weight, dim=0)[threshold_idxs]
    if sample_weights is not None:
        fps = _cumsum((1 - target) * weight, dim=0)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps
    return fps, tps, preds[threshold_idxs]


def _binned_multi_threshold_confmat(
    preds: Array,
    positive: Array,
    valid: Array,
    thresholds: Array,
) -> Array:
    """``(len_t, C, 2, 2)`` confusion tensor for every threshold, via histograms.

    TPU-native reformulation of the reference's per-threshold scatter
    (``precision_recall_curve.py:205-243``): the per-threshold TP / predicted-positive
    counts come from ``ops.multi_threshold_counts`` (fused compare-reduce einsum on
    TPU, bucketised histograms elsewhere — crossover table in
    ``ops/multi_threshold.py``), and the remaining confusion cells follow from the
    per-class totals. Identical integer counts to the reference's materialised
    comparison tensor.

    Args:
        preds: ``(N, C)`` scores.
        positive: ``(N, C)`` 0/1 ground-truth membership.
        valid: ``(N, C)`` mask of samples to count.
        thresholds: ``(len_t,)`` threshold values (any order).
    """
    from torchmetrics_tpu.ops import multi_threshold_counts

    valid_i = valid.astype(jnp.int32)
    tp, pred_pos = multi_threshold_counts(preds, positive, valid, thresholds)  # (len_t, C)
    pos_total = (positive.astype(jnp.int32) * valid_i).sum(0)[None, :]  # (1, C)
    tot_total = valid_i.sum(0)[None, :]
    fp = pred_pos - tp
    fn = pos_total - tp
    tn = (tot_total - pos_total) - fp
    return jnp.stack(
        [jnp.stack([tn, fp], axis=-1), jnp.stack([fn, tp], axis=-1)], axis=-2
    )  # (len_t, C, 2, 2)


def _adjust_threshold_arg(
    thresholds: Optional[Union[int, List[float], Array]] = None,
) -> Optional[Array]:
    """int → linspace, list → array (reference ``precision_recall_curve.py:82-89``)."""
    if isinstance(thresholds, int):
        return jnp.linspace(0, 1, thresholds)
    if isinstance(thresholds, list):
        return jnp.asarray(thresholds)
    return thresholds


def _binary_precision_recall_curve_arg_validation(
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``precision_recall_curve.py:92-120``."""
    if thresholds is not None and not isinstance(thresholds, (list, int, jnp.ndarray, jax.Array)):
        raise ValueError(
            "Expected argument `thresholds` to either be an integer, list of floats or"
            f" tensor of floats, but got {thresholds}"
        )
    if isinstance(thresholds, int) and thresholds < 2:
        raise ValueError(
            f"If argument `thresholds` is an integer, expected it to be larger than 1, but got {thresholds}"
        )
    if isinstance(thresholds, list) and not all(isinstance(t, float) and 0 <= t <= 1 for t in thresholds):
        raise ValueError(
            "If argument `thresholds` is a list, expected all elements to be floats in the [0,1] range,"
            f" but got {thresholds}"
        )
    if isinstance(thresholds, (jnp.ndarray, jax.Array)) and not thresholds.ndim == 1:
        raise ValueError("If argument `thresholds` is an tensor, expected the tensor to be 1d")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    """Reference ``precision_recall_curve.py:123-156``."""
    _check_same_shape(preds, target)
    if _is_floating(target):
        raise ValueError(
            "Expected argument `target` to be an int or long tensor with ground truth labels"
            f" but got tensor with dtype {target.dtype}"
        )
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be an floating tensor with probability/logit scores,"
            f" but got tensor with dtype {preds.dtype}"
        )
    unique_values = np.unique(np.asarray(target))
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not set(unique_values.tolist()).issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {sorted(allowed)}."
        )


def _binary_precision_recall_curve_format(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array]]:
    """Flatten, auto-sigmoid, mask ignored targets → -1 (reference ``:159-186``)."""
    preds = jnp.asarray(preds).flatten()
    target = jnp.asarray(target).flatten()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    if not bool(jnp.all((preds >= 0) & (preds <= 1))):
        preds = jax.nn.sigmoid(preds)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds


def _binary_precision_recall_curve_update(
    preds: Array,
    target: Array,
    thresholds: Optional[Array],
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (len_t, 2, 2) multi-threshold confmat via bucketised histograms (reference ``:189-243``)."""
    if thresholds is None:
        return preds, target
    valid = target >= 0
    confmat = _binned_multi_threshold_confmat(
        preds[:, None], (target > 0)[:, None], valid[:, None], thresholds
    )
    return confmat[:, 0]


def _binary_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Final curve (reference ``:246-275``)."""
    if isinstance(state, (jnp.ndarray, jax.Array)) and not isinstance(state, tuple):
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones(1, dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros(1, dtype=recall.dtype)])
        return precision, recall, thresholds

    preds, target = state
    # exact mode: drop ignored (-1) targets eagerly — dynamic size is inherent here
    keep = np.asarray(target) >= 0
    if not keep.all():
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    fps, tps, thresh = _binary_clf_curve(preds, target, pos_label=pos_label)
    precision = tps / (tps + fps)
    recall = tps / tps[-1]
    precision = jnp.concatenate([precision[::-1], jnp.ones(1, dtype=precision.dtype)])
    recall = jnp.concatenate([recall[::-1], jnp.zeros(1, dtype=recall.dtype)])
    thresh = thresh[::-1]
    return precision, recall, thresh


def binary_precision_recall_curve(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """PR curve for binary tasks (reference ``precision_recall_curve.py:278-...``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.precision_recall_curve import binary_precision_recall_curve
        >>> print(tuple(v.shape for v in binary_precision_recall_curve(preds, target, thresholds=5)))
        ((6,), (6,), (5,))
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_precision_recall_curve_compute(state, thresholds)


# --------------------------------------------------------------------------- multiclass


def _multiclass_precision_recall_curve_arg_validation(
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``precision_recall_curve.py:355-368``."""
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multiclass_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    """Reference ``precision_recall_curve.py:371-409``."""
    if not preds.ndim == target.ndim + 1:
        raise ValueError(
            f"Expected `preds` to have one more dimension than `target` but got {preds.ndim} and {target.ndim}"
        )
    if _is_floating(target):
        raise ValueError(
            f"Expected argument `target` to be an int or long tensor, but got tensor with dtype {target.dtype}"
        )
    if not _is_floating(preds):
        raise ValueError(f"Expected `preds` to be a float tensor, but got {preds.dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(
            "Expected `preds.shape[1]` to be equal to the number of classes but"
            f" got {preds.shape[1]} and {num_classes}."
        )
    if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
        raise ValueError(
            "Expected the shape of `preds` should be (N, C, ...) and the shape of `target` should be (N, ...)"
            f" but got {preds.shape} and {target.shape}"
        )
    num_unique_values = len(np.unique(np.asarray(target)))
    check = num_unique_values > num_classes if ignore_index is None else num_unique_values > num_classes + 1
    if check:
        raise RuntimeError(
            "Detected more unique values in `target` than `num_classes`. Expected only "
            f"{num_classes if ignore_index is None else num_classes + 1} but found "
            f"{num_unique_values} in `target`."
        )


def _multiclass_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array]]:
    """To (N, C) scores + flat targets; ignored → -1 (reference ``:411-442``)."""
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_classes)
    target = jnp.asarray(target).flatten()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    if not bool(jnp.all((preds >= 0) & (preds <= 1))):
        preds = jax.nn.softmax(preds, axis=1)
    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds


def _multiclass_precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (len_t, C, 2, 2) via bucketised histograms (reference ``:445-501``)."""
    if thresholds is None:
        return preds, target
    valid = target >= 0
    safe_target = jnp.where(valid, target, 0)
    target_t = jax.nn.one_hot(safe_target, num_classes, dtype=jnp.int32)  # (N, C)
    return _binned_multi_threshold_confmat(
        preds, target_t, jnp.broadcast_to(valid[:, None], preds.shape), thresholds
    )


def _multiclass_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Final per-class curves (reference ``:504-531``)."""
    if isinstance(state, (jnp.ndarray, jax.Array)) and not isinstance(state, tuple):
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones((1, num_classes), dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros((1, num_classes), dtype=recall.dtype)])
        return precision.T, recall.T, thresholds

    precision, recall, thresh = [], [], []
    for i in range(num_classes):
        res = _binary_precision_recall_curve_compute((state[0][:, i], state[1]), thresholds=None, pos_label=i)
        precision.append(res[0])
        recall.append(res[1])
        thresh.append(res[2])
    return precision, recall, thresh


def multiclass_precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """PR curves for multiclass tasks (reference ``precision_recall_curve.py:534-...``)."""
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)


# --------------------------------------------------------------------------- multilabel


def _multilabel_precision_recall_curve_arg_validation(
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``precision_recall_curve.py:640-650``."""
    _multiclass_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)


def _multilabel_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    """Reference ``precision_recall_curve.py:653-668``."""
    _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    if preds.shape[1] != num_labels:
        raise ValueError(
            "Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )


def _multilabel_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array]]:
    """To (num_samples, L) layout; ignored → negative sentinel (reference ``:671-700``)."""
    preds = jnp.moveaxis(jnp.asarray(preds), 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(jnp.asarray(target), 1, -1).reshape(-1, num_labels)
    if not bool(jnp.all((preds >= 0) & (preds <= 1))):
        preds = jax.nn.sigmoid(preds)
    thresholds = _adjust_threshold_arg(thresholds)
    if ignore_index is not None:
        idx = target == ignore_index
        sentinel = -4 * num_labels * (thresholds.shape[0] if thresholds is not None else 1)
        preds = jnp.where(idx, sentinel, preds)
        target = jnp.where(idx, sentinel, target)
    return preds, target, thresholds


def _multilabel_precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Array],
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (len_t, L, 2, 2) via bucketised histograms (reference ``:700-722``)."""
    if thresholds is None:
        return preds, target
    valid = target >= 0
    safe_target = jnp.where(valid, target, 0)
    return _binned_multi_threshold_confmat(preds, safe_target > 0, valid, thresholds)


def _multilabel_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Final per-label curves (reference ``:724-758``)."""
    if isinstance(state, (jnp.ndarray, jax.Array)) and not isinstance(state, tuple):
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones((1, num_labels), dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros((1, num_labels), dtype=recall.dtype)])
        return precision.T, recall.T, thresholds

    precision, recall, thresh = [], [], []
    for i in range(num_labels):
        preds_i = state[0][:, i]
        target_i = state[1][:, i]
        if ignore_index is not None:
            keep = np.asarray(target_i) != ignore_index
            preds_i = jnp.asarray(np.asarray(preds_i)[keep])
            target_i = jnp.asarray(np.asarray(target_i)[keep])
        res = _binary_precision_recall_curve_compute((preds_i, target_i), thresholds=None, pos_label=1)
        precision.append(res[0])
        recall.append(res[1])
        thresh.append(res[2])
    return precision, recall, thresh


def multilabel_precision_recall_curve(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """PR curves for multilabel tasks (reference ``precision_recall_curve.py:761-...``)."""
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)


def precision_recall_curve(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-routing wrapper (reference legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_recall_curve(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_recall_curve(preds, target, num_classes, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_recall_curve(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
