"""Cohen's kappa (reference ``functional/classification/cohen_kappa.py``).

Confusion-matrix-state derivative: update is the confmat scatter-add, compute is the
kappa reduce (eager epoch-end math).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
)
from torchmetrics_tpu.utilities.enums import ClassificationTaskNoMultilabel

Array = jax.Array


def _cohen_kappa_reduce(confmat: Array, weights: Optional[str] = None) -> Array:
    """Confmat → kappa (reference ``cohen_kappa.py:33-55``)."""
    confmat = confmat.astype(jnp.float32)
    n_classes = confmat.shape[0]
    sum0 = confmat.sum(axis=0, keepdims=True)
    sum1 = confmat.sum(axis=1, keepdims=True)
    expected = sum1 @ sum0 / sum0.sum()

    if weights is None or weights == "none":
        w_mat = 1.0 - jnp.eye(n_classes, dtype=confmat.dtype)
    elif weights in ("linear", "quadratic"):
        idx = jnp.arange(n_classes, dtype=confmat.dtype)
        diff = idx[:, None] - idx[None, :]
        w_mat = jnp.abs(diff) if weights == "linear" else diff**2
    else:
        raise ValueError(
            f"Received {weights} for argument ``weights`` but should be either None, 'linear' or 'quadratic'"
        )
    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def _validate_weights(weights: Optional[str]) -> None:
    if weights not in (None, "none", "linear", "quadratic"):
        raise ValueError(
            f"Expected argument `weights` to be one of None, 'none', 'linear' or 'quadratic' but got {weights}"
        )


def binary_cohen_kappa(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Kappa for binary tasks (reference ``cohen_kappa.py:58-...``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.cohen_kappa import binary_cohen_kappa
        >>> print(round(float(binary_cohen_kappa(preds, target)), 4))
        0.3333
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _validate_weights(weights)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target)
    return _cohen_kappa_reduce(confmat, weights)


def multiclass_cohen_kappa(
    preds: Array,
    target: Array,
    num_classes: int,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Kappa for multiclass tasks (reference ``cohen_kappa.py``)."""
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _validate_weights(weights)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _cohen_kappa_reduce(confmat, weights)


def cohen_kappa(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing wrapper (reference legacy API)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_cohen_kappa(preds, target, threshold, weights, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_cohen_kappa(preds, target, num_classes, weights, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
