"""Confusion matrices — binary / multiclass / multilabel.

Capability parity: reference ``functional/classification/confusion_matrix.py`` (binary
``:145-148``, multiclass ``:327``, multilabel ``:511``). TPU-first: the update is one
deterministic weighted scatter-add with static shapes — ignored samples map to a
negative bin index and are dropped by the scatter (``mode="drop"``) instead of being
boolean-filtered out (which would make shapes dynamic and break jit).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_tensor_validation,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_tensor_validation,
    _sigmoid_if_logits,
    _is_floating,
)
from torchmetrics_tpu.utilities.enums import ClassificationTask
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

_ALLOWED_NORMALIZE = ("true", "pred", "all", "none", None)


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalize a confusion matrix (reference ``confusion_matrix.py:26-59``).

    ``"true"`` divides rows (target axis), ``"pred"`` divides columns, ``"all"`` the
    whole matrix; NaNs from empty rows/cols become 0.
    """
    if normalize not in _ALLOWED_NORMALIZE:
        raise ValueError(f"Argument `normalize` needs to one of the following: {_ALLOWED_NORMALIZE}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32) if not _is_floating(confmat) else confmat
        if normalize == "true":
            confmat = confmat / confmat.sum(axis=-1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / confmat.sum(axis=-2, keepdims=True)
        elif normalize == "all":
            confmat = confmat / confmat.sum(axis=(-2, -1), keepdims=True)
        nan_elements = int(np.isnan(np.asarray(confmat)).sum())
        if nan_elements:
            confmat = jnp.nan_to_num(confmat, nan=0.0)
            rank_zero_warn(f"{nan_elements} NaN values found in confusion matrix have been replaced with zeros.")
    return confmat


def _bincount_2d(mapping: Array, weights: Array, n_bins: int) -> Array:
    """Weighted deterministic bincount; negative indices are dropped.

    Thin alias over the shared in-graph scatter-add (``utilities/data._bincount``)
    so every counting path lowers through the same single-scatter kernel.
    """
    from torchmetrics_tpu.utilities.data import _bincount

    return _bincount(mapping, minlength=n_bins, weights=weights)


# ------------------------------------------------------------------------------ binary


def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
) -> None:
    """Reference ``confusion_matrix.py:62-79``."""
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in _ALLOWED_NORMALIZE:
        raise ValueError(f"Expected argument `normalize` to be one of {_ALLOWED_NORMALIZE}, but got {normalize}.")


def _binary_confusion_matrix_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)


def _binary_confusion_matrix_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    convert_to_labels: bool = True,
) -> Tuple[Array, Array]:
    """Flatten + threshold; ignored targets become -1 (→ scatter-dropped downstream).

    Reference ``confusion_matrix.py:~118-140`` filters instead; masking keeps shapes
    static. ``convert_to_labels=False`` keeps float probabilities (PR-curve reuse).
    """
    preds = jnp.asarray(preds).flatten()
    target = jnp.asarray(target).flatten()
    if _is_floating(preds):
        preds = _sigmoid_if_logits(preds)
        if convert_to_labels:
            preds = (preds > threshold).astype(jnp.int32)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _binary_confusion_matrix_update(preds: Array, target: Array) -> Array:
    """One scatter-add into 4 bins (reference ``confusion_matrix.py:145-148``)."""
    unique_mapping = jnp.where(target < 0, -1, target * 2 + preds)
    valid = (unique_mapping >= 0).astype(jnp.int32)
    return _bincount_2d(unique_mapping, valid, 4).reshape(2, 2)


def _binary_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def binary_confusion_matrix(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """2x2 confusion matrix for binary tasks (reference ``confusion_matrix.py:151-211``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.confusion_matrix import binary_confusion_matrix
        >>> print(binary_confusion_matrix(preds, target).shape)
        (2, 2)
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target)
    return _binary_confusion_matrix_compute(confmat, normalize)


# --------------------------------------------------------------------------- multiclass


def _multiclass_confusion_matrix_arg_validation(
    num_classes: int,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
) -> None:
    """Reference ``confusion_matrix.py:214-231``."""
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in _ALLOWED_NORMALIZE:
        raise ValueError(f"Expected argument `normalize` to be one of {_ALLOWED_NORMALIZE}, but got {normalize}.")


def _multiclass_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    _multiclass_stat_scores_tensor_validation(preds, target, num_classes, "global", ignore_index)


def _multiclass_confusion_matrix_format(
    preds: Array,
    target: Array,
    ignore_index: Optional[int] = None,
    convert_to_labels: bool = True,
) -> Tuple[Array, Array]:
    """Argmax + flatten; ignored targets → -1 (reference ``confusion_matrix.py:~300-323``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and convert_to_labels:
        preds = jnp.argmax(preds, axis=1)
    if convert_to_labels:
        preds = preds.flatten()
    else:
        preds = jnp.moveaxis(preds, 1, -1).reshape(-1, preds.shape[1])
    target = target.flatten()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _multiclass_confusion_matrix_update(preds: Array, target: Array, num_classes: int) -> Array:
    """``confmat[i, j] = #{n : target==i, preds==j}`` (reference ``confusion_matrix.py:325-330``).

    On TPU: one MXU matmul of two lazily generated bf16 one-hots (``tgt_ohᵀ @
    pred_oh``) — scatters serialise on TPU while this is a fused single pass over the
    labels (same pattern as ``ops/stat_counts.py``); exact while ``N < 2**24``.
    Elsewhere (or at f32-exactness risk, or at the nominal metrics' huge category
    counts where O(N*C²) matmul work loses to the O(N) scatter): the deterministic
    scatter-add bincount.
    """

    from torchmetrics_tpu.ops._dispatch import inputs_on_tpu

    n = preds.shape[0] if preds.ndim else 1
    if inputs_on_tpu(preds) and n < (1 << 24) and num_classes <= 1024:
        ci = jnp.arange(num_classes, dtype=jnp.int32)
        valid = (target >= 0).astype(jnp.bfloat16)
        tgt_oh = (target[:, None] == ci).astype(jnp.bfloat16) * valid[:, None]  # (N, C)
        pred_oh = (preds[:, None] == ci).astype(jnp.bfloat16)
        dims = (((0,), (0,)), ((), ()))
        out = jax.lax.dot_general(tgt_oh, pred_oh, dims, preferred_element_type=jnp.float32)
        return out.astype(jnp.int32)
    # out-of-range preds/target are DROPPED (matching the one-hot path and the
    # documented nominal-metrics contract) — without the preds bound an invalid
    # code would alias into a wrong cell of the flattened bincount
    invalid = (target < 0) | (target >= num_classes) | (preds < 0) | (preds >= num_classes)
    unique_mapping = jnp.where(invalid, -1, target * num_classes + preds)
    valid = (unique_mapping >= 0).astype(jnp.int32)
    return _bincount_2d(unique_mapping, valid, num_classes * num_classes).reshape(num_classes, num_classes)


def _multiclass_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multiclass_confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """CxC confusion matrix (reference ``confusion_matrix.py:341-401``)."""
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _multiclass_confusion_matrix_compute(confmat, normalize)


# --------------------------------------------------------------------------- multilabel


def _multilabel_confusion_matrix_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
) -> None:
    """Reference ``confusion_matrix.py:404-424``."""
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in _ALLOWED_NORMALIZE:
        raise ValueError(f"Expected argument `normalize` to be one of {_ALLOWED_NORMALIZE}, but got {normalize}.")


def _multilabel_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _multilabel_stat_scores_tensor_validation(preds, target, num_labels, "global", ignore_index)


def _multilabel_confusion_matrix_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    should_threshold: bool = True,
) -> Tuple[Array, Array]:
    """To (num_samples, num_labels) label layout; ignored entries → large negative
    sentinel so their bin index stays negative (reference ``confusion_matrix.py:480-505``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if _is_floating(preds):
        preds = _sigmoid_if_logits(preds)
        if should_threshold:
            preds = (preds > threshold).astype(jnp.int32)
    preds = jnp.moveaxis(preds, 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(target, 1, -1).reshape(-1, num_labels)
    if ignore_index is not None:
        idx = target == ignore_index
        sentinel = -4 * num_labels
        preds = jnp.where(idx, sentinel, preds)
        target = jnp.where(idx, sentinel, target)
    return preds, target


def _multilabel_confusion_matrix_update(preds: Array, target: Array, num_labels: int) -> Array:
    """One scatter-add into 4·L bins → (L, 2, 2) (reference ``confusion_matrix.py:508-513``)."""
    unique_mapping = ((2 * target + preds) + 4 * jnp.arange(num_labels)).flatten()
    unique_mapping = jnp.where(unique_mapping >= 0, unique_mapping, -1)
    valid = (unique_mapping >= 0).astype(jnp.int32)
    return _bincount_2d(unique_mapping, valid, 4 * num_labels).reshape(num_labels, 2, 2)


def _multilabel_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multilabel_confusion_matrix(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """(L, 2, 2) per-label confusion matrices (reference ``confusion_matrix.py:516-...``)."""
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, num_labels)
    return _multilabel_confusion_matrix_compute(confmat, normalize)


def confusion_matrix(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing wrapper (reference ``confusion_matrix.py`` legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_confusion_matrix(preds, target, threshold, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_confusion_matrix(preds, target, num_classes, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, normalize, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
