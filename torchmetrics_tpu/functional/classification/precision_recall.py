"""Precision & Recall — binary / multiclass / multilabel (+ task routers).

Capability parity: reference ``functional/classification/precision_recall.py``
(reduce ``:38-59``).
"""

from __future__ import annotations

from typing import Optional

import jax

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_pipeline,
)
from torchmetrics_tpu.utilities.compute import _adjust_weights_safe_divide, _safe_divide, _sum_axis
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _precision_recall_reduce(
    stat: str,
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reference ``precision_recall.py:38-59``: precision divides by fp, recall by fn."""
    different_stat = fp if stat == "precision" else fn
    if average == "binary":
        return _safe_divide(tp, tp + different_stat)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = _sum_axis(tp, axis)
        fn = _sum_axis(fn, axis)
        different_stat = _sum_axis(different_stat, axis)
        return _safe_divide(tp, tp + different_stat)
    score = _safe_divide(tp, tp + different_stat)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn)


def _make_prf(stat: str):
    def binary_fn(
        preds: Array,
        target: Array,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
    ) -> Array:
        tp, fp, tn, fn = _binary_stat_scores_pipeline(
            preds, target, threshold, multidim_average, ignore_index, validate_args
        )
        return _precision_recall_reduce(stat, tp, fp, tn, fn, average="binary", multidim_average=multidim_average)

    def multiclass_fn(
        preds: Array,
        target: Array,
        num_classes: int,
        average: Optional[str] = "macro",
        top_k: int = 1,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
    ) -> Array:
        tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
        return _precision_recall_reduce(stat, tp, fp, tn, fn, average=average, multidim_average=multidim_average)

    def multilabel_fn(
        preds: Array,
        target: Array,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
    ) -> Array:
        tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
        return _precision_recall_reduce(
            stat, tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True
        )

    return binary_fn, multiclass_fn, multilabel_fn


binary_precision, multiclass_precision, multilabel_precision = _make_prf("precision")
binary_precision.__name__ = "binary_precision"
multiclass_precision.__name__ = "multiclass_precision"
multilabel_precision.__name__ = "multilabel_precision"
binary_precision.__doc__ = "Precision = tp / (tp + fp) for binary tasks (reference ``precision_recall.py``)."
multiclass_precision.__doc__ = "Precision for multiclass tasks (reference ``precision_recall.py``)."
multilabel_precision.__doc__ = "Precision for multilabel tasks (reference ``precision_recall.py``)."

binary_recall, multiclass_recall, multilabel_recall = _make_prf("recall")
binary_recall.__name__ = "binary_recall"
multiclass_recall.__name__ = "multiclass_recall"
multilabel_recall.__name__ = "multilabel_recall"
binary_recall.__doc__ = "Recall = tp / (tp + fn) for binary tasks (reference ``precision_recall.py``)."
multiclass_recall.__doc__ = "Recall for multiclass tasks (reference ``precision_recall.py``)."
multilabel_recall.__doc__ = "Recall for multilabel tasks (reference ``precision_recall.py``)."


def _route(
    stat: str,
    preds: Array,
    target: Array,
    task: str,
    threshold: float,
    num_classes: Optional[int],
    num_labels: Optional[int],
    average: Optional[str],
    multidim_average: str,
    top_k: int,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Array:
    binary_fn, multiclass_fn, multilabel_fn = (
        (binary_precision, multiclass_precision, multilabel_precision)
        if stat == "precision"
        else (binary_recall, multiclass_recall, multilabel_recall)
    )
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_fn(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_fn(preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_fn(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")


def precision(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing precision (reference ``precision_recall.py`` legacy API)."""
    return _route(
        "precision", preds, target, task, threshold, num_classes, num_labels,
        average, multidim_average, top_k, ignore_index, validate_args,
    )


def recall(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing recall (reference ``precision_recall.py`` legacy API)."""
    return _route(
        "recall", preds, target, task, threshold, num_classes, num_labels,
        average, multidim_average, top_k, ignore_index, validate_args,
    )
