"""Multilabel ranking metrics (reference ``functional/classification/ranking.py``).

Coverage error, label-ranking AP, label-ranking loss. The reference's per-sample
Python loop for ranking AP is replaced with fully vectorized rank computations
(argsort-based dense ranks with tie averaging via sorted-segment means is not needed:
the reference's ``_rank_data`` produces *max* ranks of ties via cumsum of unique
counts; we reproduce that exactly with a sort + searchsorted formulation).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
)
from torchmetrics_tpu.functional.classification.stat_scores import _is_floating
from torchmetrics_tpu.utilities.compute import _safe_divide

Array = jax.Array


def _rank_data(x: Array) -> Array:
    """Max-rank of each value among ties (reference ``ranking.py:27-33``).

    ``searchsorted(sorted, x, 'right')`` equals cumsum-of-counts indexed at each
    element's unique id — identical semantics, no ``unique`` (jit-friendly).
    """
    sorted_x = jnp.sort(x)
    return jnp.searchsorted(sorted_x, x, side="right")


def _ranking_reduce(score: Array, n_elements: Array) -> Array:
    """Reference ``ranking.py:36-37``.

    ``n_elements`` is an accumulated sample count: a zero-count segment
    (compute before any update reached this shard) yields the documented
    zero, not 0/0 NaN.
    """
    return _safe_divide(score, n_elements)


def _multilabel_ranking_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    """Reference ``ranking.py:40-45``."""
    _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    if not _is_floating(preds):
        raise ValueError(f"Expected preds tensor to be floating point, but received input with dtype {preds.dtype}")


def _multilabel_coverage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``ranking.py:48-55``."""
    offset = jnp.where(target == 0, jnp.abs(preds.min()) + 10, 0.0)
    preds_mod = preds + offset
    preds_min = preds_mod.min(axis=1)
    coverage = jnp.sum(preds >= preds_min[:, None], axis=1).astype(jnp.float32)
    return coverage.sum(), jnp.asarray(coverage.size, dtype=jnp.int32)


def multilabel_coverage_error(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Coverage error (reference ``ranking.py:58-108``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.75, 0.05, 0.35], [0.45, 0.75, 0.05], [0.05, 0.65, 0.75]])
        >>> target = jnp.asarray([[1, 0, 1], [0, 1, 0], [0, 1, 1]])
        >>> from torchmetrics_tpu.functional.classification.ranking import multilabel_coverage_error
        >>> print(round(float(multilabel_coverage_error(preds, target, num_labels=3)), 4))
        1.6667
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(
        preds, target, num_labels, threshold=0.0, ignore_index=ignore_index, should_threshold=False
    )
    coverage, total = _multilabel_coverage_error_update(preds, target)
    return _ranking_reduce(coverage, total)


def _multilabel_ranking_average_precision_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Label-ranking AP, vectorized over samples (reference loops per-sample, ``ranking.py:111-128``).

    For each sample i and relevant label j: score contribution is
    (rank of j among relevant scores) / (rank of j among all scores), averaged over
    relevant j — unless 0 or all labels are relevant, in which case the sample scores 1.
    """
    neg_preds = -preds
    n_preds, n_labels = neg_preds.shape
    relevant = target == 1
    n_relevant = relevant.sum(axis=1)

    def per_sample(scores, rel):
        # rank among all labels (max-rank over ties)
        rank_all = _rank_data(scores).astype(jnp.float32)
        # rank among relevant labels only: count relevant entries with value <= scores[j]
        big = jnp.where(rel, scores, jnp.inf)
        sorted_rel = jnp.sort(big)
        rank_rel = jnp.searchsorted(sorted_rel, scores, side="right").astype(jnp.float32)
        ratio = jnp.where(rel, rank_rel / rank_all, 0.0)
        k = rel.sum()
        mean_ratio = jnp.where(k > 0, ratio.sum() / jnp.maximum(k, 1), 1.0)
        return jnp.where((k > 0) & (k < n_labels), mean_ratio, 1.0)

    scores = jax.vmap(per_sample)(neg_preds, relevant)
    del n_relevant
    return scores.sum(), jnp.asarray(n_preds, dtype=jnp.int32)


def multilabel_ranking_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label ranking average precision (reference ``ranking.py:131-180``)."""
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(
        preds, target, num_labels, threshold=0.0, ignore_index=ignore_index, should_threshold=False
    )
    score, total = _multilabel_ranking_average_precision_update(preds, target)
    return _ranking_reduce(score, total)


def _multilabel_ranking_loss_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Reference ``ranking.py:183-210`` — masked instead of filtered."""
    n_preds, n_labels = preds.shape
    relevant = target == 1
    n_relevant = relevant.sum(axis=1)
    mask = (n_relevant > 0) & (n_relevant < n_labels)

    inverse = jnp.argsort(jnp.argsort(preds, axis=1), axis=1)
    per_label_loss = ((n_labels - inverse) * relevant).astype(jnp.float32)
    correction = 0.5 * n_relevant * (n_relevant + 1)
    denom = n_relevant * (n_labels - n_relevant)
    loss = (per_label_loss.sum(axis=1) - correction) / jnp.maximum(denom, 1)
    loss = jnp.where(mask, loss, 0.0)
    return loss.sum(), jnp.asarray(n_preds, dtype=jnp.int32)


def multilabel_ranking_loss(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label ranking loss (reference ``ranking.py:213-...``)."""
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(
        preds, target, num_labels, threshold=0.0, ignore_index=ignore_index, should_threshold=False
    )
    loss, total = _multilabel_ranking_loss_update(preds, target)
    return _ranking_reduce(loss, total)
