"""Recall at fixed precision (reference ``functional/classification/recall_fixed_precision.py``).

Operating-point selection over the PR curve: the curve state machinery is shared with
``precision_recall_curve.py``; the selection itself is a tiny host reduction over the
already-computed curve (lexicographic max, matching the reference's tuple-max).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _lexi_max_at_constraint(
    objective: Array, constrained: Array, thresholds: Array, min_constraint: float
) -> Tuple[Array, Array]:
    """Max objective among points whose constrained value clears the floor.

    Ties broken by the constrained value, then threshold — the reference's
    ``max((obj, con, t) ...)`` tuple ordering (``recall_fixed_precision.py:40-63``).
    Returns (0.0, 1e6) when no point qualifies.
    """
    obj = np.asarray(objective, dtype=np.float64)
    con = np.asarray(constrained, dtype=np.float64)
    thr = np.asarray(thresholds, dtype=np.float64)
    n = min(len(obj), len(con), len(thr))
    obj, con, thr = obj[:n], con[:n], thr[:n]
    mask = con >= min_constraint
    if not mask.any():
        return jnp.asarray(0.0, dtype=jnp.float32), jnp.asarray(1e6, dtype=jnp.float32)
    obj, con, thr = obj[mask], con[mask], thr[mask]
    best = np.lexsort((thr, con, obj))[-1]
    max_obj = obj[best]
    best_thr = thr[best] if max_obj != 0.0 else 1e6
    return jnp.asarray(max_obj, dtype=jnp.float32), jnp.asarray(best_thr, dtype=jnp.float32)


def _recall_at_precision(
    precision: Array, recall: Array, thresholds: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Highest recall whose precision clears the floor (reference ``:40-63``)."""
    # the curve appends a (precision=1, recall=0) endpoint with no threshold; the
    # reference's zip() implicitly drops it, as does the truncation here
    return _lexi_max_at_constraint(recall, precision, thresholds, min_precision)


def _validate_fixed_point_arg(value: float, name: str) -> None:
    """Shared [0,1]-float check for the min_precision/min_recall/min_sensitivity floors."""
    if not isinstance(value, float) or not (0 <= value <= 1):
        raise ValueError(f"Expected argument `{name}` to be an float in the [0,1] range, but got {value}")


def _binary_recall_at_fixed_precision_arg_validation(
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    arg_name: str = "min_precision",
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    _validate_fixed_point_arg(min_precision, arg_name)


def _binary_recall_at_fixed_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    min_precision: float,
    pos_label: int = 1,
    reduce_fn: Callable = _recall_at_precision,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _binary_precision_recall_curve_compute(state, thresholds, pos_label)
    return reduce_fn(precision, recall, thresholds, min_precision)


def binary_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    r"""Highest recall given a minimum precision floor, binary task (reference ``:84-154``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.recall_fixed_precision import binary_recall_at_fixed_precision
        >>> print(tuple(round(float(v), 4) for v in binary_recall_at_fixed_precision(preds, target, min_precision=0.5)))
        (1.0, 0.35)
    """
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_recall_at_fixed_precision_compute(state, thresholds, min_precision)


def _multiclass_recall_at_fixed_precision_arg_validation(
    num_classes: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    arg_name: str = "min_precision",
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    _validate_fixed_point_arg(min_precision, arg_name)


def _multiclass_recall_at_fixed_precision_arg_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    min_precision: float,
    reduce_fn: Callable = _recall_at_precision,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if not isinstance(precision, list):
        # one D2H fetch of the full (C, T) arrays, not three per class
        precision, recall, thr = np.asarray(precision), np.asarray(recall), np.asarray(thresholds)
        res = [reduce_fn(p, r, thr, min_precision) for p, r in zip(precision, recall)]
    else:
        res = [reduce_fn(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multiclass_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    r"""Highest per-class recall given a minimum precision floor (reference ``:186-263``)."""
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_recall_at_fixed_precision_arg_compute(state, num_classes, thresholds, min_precision)


def _multilabel_recall_at_fixed_precision_arg_validation(
    num_labels: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    arg_name: str = "min_precision",
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    _validate_fixed_point_arg(min_precision, arg_name)


def _multilabel_recall_at_fixed_precision_arg_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int],
    min_precision: float,
    reduce_fn: Callable = _recall_at_precision,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _multilabel_precision_recall_curve_compute(
        state, num_labels, thresholds, ignore_index
    )
    if not isinstance(precision, list):
        # one D2H fetch of the full (L, T) arrays, not three per label
        precision, recall, thr = np.asarray(precision), np.asarray(recall), np.asarray(thresholds)
        res = [reduce_fn(p, r, thr, min_precision) for p, r in zip(precision, recall)]
    else:
        res = [reduce_fn(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multilabel_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    r"""Highest per-label recall given a minimum precision floor (reference ``:298-377``)."""
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_recall_at_fixed_precision_arg_compute(state, num_labels, thresholds, ignore_index, min_precision)


def recall_at_fixed_precision(
    preds: Array,
    target: Array,
    task: str,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-routing wrapper (reference ``:380-422``)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_recall_at_fixed_precision(preds, target, min_precision, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_recall_at_fixed_precision(
            preds, target, num_classes, min_precision, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_recall_at_fixed_precision(
            preds, target, num_labels, min_precision, thresholds, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
