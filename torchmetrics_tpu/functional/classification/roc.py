"""ROC curves (reference ``functional/classification/roc.py``).

Shares the PR-curve dual state: binned confmats (jit-safe) or exact sort-based curves.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _binary_clf_curve,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.enums import ClassificationTask
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _is_state_tensor(state) -> bool:
    return isinstance(state, (jnp.ndarray, jax.Array)) and not isinstance(state, tuple)


def _binary_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """fpr/tpr/thresholds (reference ``roc.py:40-81``)."""
    if _is_state_tensor(state) and thresholds is not None:
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        tns = state[:, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1]
        fpr = _safe_divide(fps, fps + tns)[::-1]
        return fpr, tpr, thresholds[::-1]

    preds, target = state
    keep = np.asarray(target) >= 0
    if not keep.all():
        preds = jnp.asarray(np.asarray(preds)[keep])
        target = jnp.asarray(np.asarray(target)[keep])
    fps, tps, thresh = _binary_clf_curve(preds, target, pos_label=pos_label)
    # prepend a point so the curve starts at (0, 0)
    tps = jnp.concatenate([jnp.zeros(1, dtype=tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, dtype=fps.dtype), fps])
    thresh = jnp.concatenate([jnp.ones(1, dtype=thresh.dtype), thresh])
    if float(fps[-1]) <= 0:
        rank_zero_warn(
            "No negative samples in targets, false positive value should be meaningless."
            " Returning zero tensor in false positive score",
            UserWarning,
        )
        fpr = jnp.zeros_like(thresh)
    else:
        fpr = fps / fps[-1]
    if float(tps[-1]) <= 0:
        rank_zero_warn(
            "No positive samples in targets, true positive value should be meaningless."
            " Returning zero tensor in true positive score",
            UserWarning,
        )
        tpr = jnp.zeros_like(thresh)
    else:
        tpr = tps / tps[-1]
    return fpr, tpr, thresh


def binary_roc(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """ROC for binary tasks (reference ``roc.py:84-...``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.roc import binary_roc
        >>> print(tuple(v.shape for v in binary_roc(preds, target, thresholds=5)))
        ((5,), (5,), (5,))
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_roc_compute(state, thresholds)


def _multiclass_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Per-class fpr/tpr (reference ``roc.py:161-190``)."""
    if _is_state_tensor(state) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1].T
        fpr = _safe_divide(fps, fps + tns)[::-1].T
        return fpr, tpr, thresholds[::-1]

    fpr, tpr, thresh = [], [], []
    for i in range(num_classes):
        res = _binary_roc_compute((state[0][:, i], state[1]), thresholds=None, pos_label=i)
        fpr.append(res[0])
        tpr.append(res[1])
        thresh.append(res[2])
    return fpr, tpr, thresh


def multiclass_roc(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """ROC for multiclass tasks (reference ``roc.py:193-...``)."""
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_roc_compute(state, num_classes, thresholds)


def _multilabel_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Per-label fpr/tpr (reference ``roc.py:292-325``)."""
    if _is_state_tensor(state) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        tpr = _safe_divide(tps, tps + fns)[::-1].T
        fpr = _safe_divide(fps, fps + tns)[::-1].T
        return fpr, tpr, thresholds[::-1]

    fpr, tpr, thresh = [], [], []
    for i in range(num_labels):
        preds_i = state[0][:, i]
        target_i = state[1][:, i]
        if ignore_index is not None:
            keep = np.asarray(target_i) != ignore_index
            preds_i = jnp.asarray(np.asarray(preds_i)[keep])
            target_i = jnp.asarray(np.asarray(target_i)[keep])
        res = _binary_roc_compute((preds_i, target_i), thresholds=None, pos_label=1)
        fpr.append(res[0])
        tpr.append(res[1])
        thresh.append(res[2])
    return fpr, tpr, thresh


def multilabel_roc(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """ROC for multilabel tasks (reference ``roc.py:328-...``)."""
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)


def roc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-routing wrapper (reference legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_roc(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_roc(preds, target, num_classes, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_roc(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
