"""Hamming distance — binary / multiclass / multilabel (+ task router).

Capability parity: reference ``functional/classification/hamming.py`` (reduce ``:38-84``).
"""

from __future__ import annotations

from typing import Optional

import jax

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_pipeline,
)
from torchmetrics_tpu.utilities.compute import _adjust_weights_safe_divide, _safe_divide, _sum_axis
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _hamming_distance_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reference ``hamming.py:38-84``: 1 - accuracy-style score."""
    if average == "binary":
        return 1 - _safe_divide(tp + tn, tp + fp + tn + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = _sum_axis(tp, axis)
        fn = _sum_axis(fn, axis)
        if multilabel:
            fp = _sum_axis(fp, axis)
            tn = _sum_axis(tn, axis)
            return 1 - _safe_divide(tp + tn, tp + tn + fp + fn)
        return 1 - _safe_divide(tp, tp + fn)
    score = 1 - _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else 1 - _safe_divide(tp, tp + fn)
    return _adjust_weights_safe_divide(score, average, multilabel, tp, fp, fn)


def binary_hamming_distance(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Hamming distance for binary tasks (reference ``hamming.py``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.hamming import binary_hamming_distance
        >>> print(round(float(binary_hamming_distance(preds, target)), 4))
        0.3333
    """
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _hamming_distance_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_hamming_distance(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Hamming distance for multiclass tasks (reference ``hamming.py``)."""
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _hamming_distance_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_hamming_distance(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Hamming distance for multilabel tasks (reference ``hamming.py``)."""
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )
    return _hamming_distance_reduce(
        tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True
    )


def hamming_distance(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing hamming distance (reference ``hamming.py`` legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_hamming_distance(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_hamming_distance(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_hamming_distance(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
