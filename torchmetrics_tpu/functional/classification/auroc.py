"""AUROC (reference ``functional/classification/auroc.py``).

Area under the ROC curve — reuses the PR-curve dual state via the ROC computes.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _is_state_tensor,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.utilities.compute import _auc_compute_without_check, _safe_divide
from torchmetrics_tpu.utilities.data import _bincount
from torchmetrics_tpu.utilities.enums import ClassificationTask
from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _reduce_auroc(
    fpr: Union[Array, List[Array]],
    tpr: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reduce per-class AUCs (reference ``auroc.py:45-69``)."""
    if isinstance(fpr, (jnp.ndarray, jax.Array)) and not isinstance(fpr, (list, tuple)):
        res = _auc_compute_without_check(fpr, tpr, 1.0, axis=1)
    else:
        res = jnp.stack([_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)])
    if average is None or average == "none":
        return res
    if bool(jnp.isnan(res).any()):
        rank_zero_warn(
            f"Average precision score for one or more classes was `nan`. Ignoring these classes in {average}-average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.sum(jnp.where(idx, res, 0.0)) / jnp.sum(idx)
    if average == "weighted" and weights is not None:
        w = jnp.where(idx, weights, 0.0)
        w = _safe_divide(w, jnp.sum(w))
        return jnp.sum(jnp.where(idx, res, 0.0) * w)
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_auroc_arg_validation(
    max_fpr: Optional[float] = None,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``auroc.py:72-79``."""
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
        raise ValueError(f"Arguments `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")


def _binary_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    max_fpr: Optional[float] = None,
    pos_label: int = 1,
) -> Array:
    """Reference ``auroc.py:82-110`` incl. McClish partial-AUC correction."""
    fpr, tpr, _ = _binary_roc_compute(state, thresholds, pos_label)
    if max_fpr is None or max_fpr == 1:
        return _auc_compute_without_check(fpr, tpr, 1.0)

    max_area = jnp.asarray(max_fpr, dtype=fpr.dtype)
    stop = int(jnp.searchsorted(fpr, max_area, side="right"))
    weight = (max_area - fpr[stop - 1]) / (fpr[stop] - fpr[stop - 1])
    interp_tpr = tpr[stop - 1] + weight * (tpr[stop] - tpr[stop - 1])
    tpr = jnp.concatenate([tpr[:stop], interp_tpr.reshape(1)])
    fpr = jnp.concatenate([fpr[:stop], max_area.reshape(1)])
    partial_auc = _auc_compute_without_check(fpr, tpr, 1.0)
    min_area = 0.5 * max_area**2
    return 0.5 * (1 + (partial_auc - min_area) / (max_area - min_area))


def binary_auroc(
    preds: Array,
    target: Array,
    max_fpr: Optional[float] = None,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AUROC for binary tasks (reference ``auroc.py:113-176``)."""
    if validate_args:
        _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_auroc_compute(state, thresholds, max_fpr)


def _multiclass_auroc_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``auroc.py:179-188``."""
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None) but got {average}"
        )


def _multiclass_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    """Reference ``auroc.py:191-203``."""
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    if thresholds is None:
        target = state[1]
        keep = np.asarray(target) >= 0
        weights = _bincount(jnp.asarray(np.asarray(target)[keep]), minlength=num_classes).astype(jnp.float32)
    else:
        # tp+fn (positives per class) is threshold-invariant; read it off threshold 0
        weights = state[0][:, 1, :].sum(-1).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=weights)


def multiclass_auroc(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AUROC for multiclass tasks (reference ``auroc.py:206-290``)."""
    if validate_args:
        _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_auroc_compute(state, num_classes, average, thresholds)


def _multilabel_auroc_arg_validation(
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``auroc.py:293-302``."""
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None) but got {average}"
        )


def _multilabel_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    """Reference ``auroc.py:305-331``."""
    if average == "micro":
        if _is_state_tensor(state) and thresholds is not None:
            return _binary_auroc_compute(state.sum(1), thresholds, max_fpr=None)
        preds = state[0].flatten()
        target = state[1].flatten()
        if ignore_index is not None:
            keep = np.asarray(target) != ignore_index
            preds = jnp.asarray(np.asarray(preds)[keep])
            target = jnp.asarray(np.asarray(target)[keep])
        return _binary_auroc_compute((preds, target), thresholds, max_fpr=None)

    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    if thresholds is None:
        weights = jnp.sum(state[1] == 1, axis=0).astype(jnp.float32)
    else:
        weights = state[0][:, 1, :].sum(-1).astype(jnp.float32)
    return _reduce_auroc(fpr, tpr, average, weights=weights)


def multilabel_auroc(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """AUROC for multilabel tasks (reference ``auroc.py:334-...``)."""
    if validate_args:
        _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_auroc_compute(state, num_labels, average, thresholds, ignore_index)


def auroc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-routing wrapper (reference legacy API).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional import auroc
        >>> preds = jnp.asarray([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.asarray([0, 0, 1, 1, 1])
        >>> print(float(auroc(preds, target, task='binary')))
        0.5
    """
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_auroc(preds, target, max_fpr, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_auroc(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_auroc(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
