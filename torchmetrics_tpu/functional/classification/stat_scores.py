"""Stat-scores (tp/fp/tn/fn) — the base of the classification family.

Capability parity: reference ``src/torchmetrics/functional/classification/stat_scores.py``
(binary ``:25-211``, multiclass ``:213-553``, multilabel ``:555-803``). Same staged
decomposition (``_arg_validation`` → ``_tensor_validation`` → ``_format`` → ``_update`` →
``_compute``) but TPU-first:

* ``ignore_index`` is handled by **masking, never boolean filtering** — every stage keeps
  static shapes so the whole update lowers to one XLA graph. The sentinel trick: ignored
  targets become ``-1``, which matches neither the positive (``==1``) nor negative
  (``==0``) comparisons, so they drop out of all four counters for free.
* The multiclass confusion-matrix path is a single weighted scatter-add (deterministic on
  XLA by construction — the reference needs a loop fallback, ``utilities/data.py:211-241``).
* Logit auto-normalization (sigmoid when outside [0,1]) is branch-free via ``jnp.where``
  on an ``all()`` predicate, so it works under ``jit``.

Tensor validation runs host-side (numpy) and only when ``validate_args=True`` — keep it
outside ``jit``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.compute import _safe_divide
from torchmetrics_tpu.utilities.data import select_topk
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _sigmoid_if_logits(preds: Array) -> Array:
    """Apply sigmoid iff any value falls outside [0, 1] — branch-free, jit-safe.

    Reference semantics (``stat_scores.py:100-104``): float preds outside the unit
    interval are treated as logits.
    """
    is_probs = jnp.all((preds >= 0) & (preds <= 1))
    return jnp.where(is_probs, preds, jax.nn.sigmoid(preds))


def _count_stats(preds: Array, target: Array, sum_axis) -> Tuple[Array, Array, Array, Array]:
    """tp/fp/tn/fn counters; targets masked to -1 contribute to none of them."""
    tp = jnp.sum((target == preds) & (target == 1), axis=sum_axis).squeeze()
    fn = jnp.sum((target != preds) & (target == 1), axis=sum_axis).squeeze()
    fp = jnp.sum((target != preds) & (target == 0), axis=sum_axis).squeeze()
    tn = jnp.sum((target == preds) & (target == 0), axis=sum_axis).squeeze()
    return tp, fp, tn, fn


# ------------------------------------------------------------------------------ binary


def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``stat_scores.py:25-44``."""
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Host-side checks (reference ``stat_scores.py:47-85``)."""
    _check_same_shape(preds, target)
    unique_values = np.unique(np.asarray(target))
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not set(unique_values.tolist()).issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {sorted(allowed)}."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if not set(unique_values.tolist()).issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {unique_values} but expected only"
                " the following values [0,1] since `preds` is a label tensor."
            )
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError("Expected input to be atleast 2D when multidim_average is set to `samplewise`")


def _binary_stat_scores_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    """To label format: auto-sigmoid, threshold, flatten, mask ignored → -1 (reference ``stat_scores.py:88-114``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if _is_floating(preds):
        preds = (_sigmoid_if_logits(preds) > threshold).astype(jnp.int32)
    preds = preds.reshape(preds.shape[0], -1)
    target = target.reshape(target.shape[0], -1)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _binary_stat_scores_update(
    preds: Array,
    target: Array,
    multidim_average: str = "global",
) -> Tuple[Array, Array, Array, Array]:
    """Reference ``stat_scores.py:117-128``."""
    sum_axis = (0, 1) if multidim_average == "global" else 1
    return _count_stats(preds, target, sum_axis)


def _binary_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, multidim_average: str = "global"
) -> Array:
    """Stack [tp, fp, tn, fn, support] (reference ``stat_scores.py:131-135``)."""
    return jnp.stack([tp, fp, tn, fn, tp + fn], axis=0 if multidim_average == "global" else 1).squeeze()


def binary_stat_scores(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for binary tasks (reference ``stat_scores.py:138-210``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.stat_scores import binary_stat_scores
        >>> print([round(float(x), 4) for x in binary_stat_scores(preds, target)])
        [2.0, 1.0, 2.0, 1.0, 3.0]
    """
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# --------------------------------------------------------------------------- multiclass


def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``stat_scores.py:213-245``."""
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) or top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multiclass_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Host-side checks (reference ``stat_scores.py:248-316``)."""
    if preds.ndim == target.ndim + 1:
        if not _is_floating(preds):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError(
                "If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                " equal to number of classes."
            )
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        if multidim_average != "global" and preds.ndim < 3:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should "
                " atleast 3D when multidim_average is set to `samplewise`"
            )
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError(
                "When `preds` and `target` have the same shape, the shape of `preds` should "
                " atleast 2D when multidim_average is set to `samplewise`"
            )
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )

    num_unique_values = len(np.unique(np.asarray(target)))
    check = num_unique_values > num_classes if ignore_index is None else num_unique_values > num_classes + 1
    if check:
        raise RuntimeError(
            "Detected more unique values in `target` than `num_classes`. Expected only"
            f" {num_classes if ignore_index is None else num_classes + 1} but found"
            f" {num_unique_values} in `target`."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if len(unique_values) > num_classes:
            raise RuntimeError(
                "Detected more unique values in `preds` than `num_classes`. Expected only"
                f" {num_classes} but found {len(unique_values)} in `preds`."
            )


def _multiclass_stat_scores_format(
    preds: Array,
    target: Array,
    top_k: int = 1,
) -> Tuple[Array, Array]:
    """Argmax logits (when top_k==1), flatten extra dims (reference ``stat_scores.py:319-334``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and top_k == 1:
        preds = jnp.argmax(preds, axis=1)
    preds = preds.reshape(*preds.shape[:2], -1) if top_k != 1 else preds.reshape(preds.shape[0], -1)
    target = target.reshape(target.shape[0], -1)
    return preds, target


def _multiclass_stat_scores_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """tp/fp/tn/fn (reference ``stat_scores.py:337-411``), mask-based.

    Three static paths chosen at trace time (all jit-safe):
    1. samplewise / top-k — one-hot comparison with ignored rows masked to -1;
    2. micro — direct equality counting with a validity mask;
    3. otherwise — confusion matrix as one weighted scatter-add, stats from its diagonal.
    """
    valid = jnp.ones(target.shape, dtype=bool) if ignore_index is None else target != ignore_index

    if multidim_average == "samplewise" or top_k != 1:
        if top_k > 1:
            preds_oh = jnp.moveaxis(select_topk(preds, topk=top_k, dim=1), 1, -1)
        else:
            safe_preds = jnp.clip(preds, 0, num_classes - 1)
            preds_oh = jax.nn.one_hot(safe_preds, num_classes, dtype=jnp.int32)
            # out-of-range ignored preds one-hot to nothing (ref drops the extra column)
            pred_valid = (preds >= 0) & (preds < num_classes)
            preds_oh = preds_oh * pred_valid[..., None].astype(jnp.int32)
        safe_target = jnp.clip(target, 0, num_classes - 1)
        target_oh = jax.nn.one_hot(safe_target, num_classes, dtype=jnp.int32)
        # ignored rows → -1 sentinel: matches neither ==1 nor ==0 in any counter
        target_oh = jnp.where(valid[..., None], target_oh, -1)
        sum_axis = (0, 1) if multidim_average == "global" else (1,)
        tp = jnp.sum((target_oh == preds_oh) & (target_oh == 1), axis=sum_axis)
        fn = jnp.sum((target_oh != preds_oh) & (target_oh == 1), axis=sum_axis)
        fp = jnp.sum((target_oh != preds_oh) & (target_oh == 0), axis=sum_axis)
        tn = jnp.sum((target_oh == preds_oh) & (target_oh == 0), axis=sum_axis)
        return tp, fp, tn, fn

    preds = preds.flatten()
    target = target.flatten()
    valid = valid.flatten()
    if average == "micro":
        n_valid = jnp.sum(valid)
        tp = jnp.sum((preds == target) & valid)
        fp = n_valid - tp
        fn = n_valid - tp
        tn = num_classes * n_valid - (fp + fn + tp)
        return tp, fp, tn, fn

    # confusion-matrix path: ONE deterministic scatter-add + dense reductions.
    # Measured on TPU v5e this beats three per-class bincount scatters ~2x (248 µs vs
    # 117 µs at 8192x1000): scatter is the expensive primitive on TPU, and the (C, C)
    # matrix's dense diag/row/col reductions are nearly free next to a second and
    # third scatter. Invalid rows get weight 0 and a -1 index (dropped).
    unique_mapping = target * num_classes + preds
    unique_mapping = jnp.where(valid, unique_mapping, -1)  # -1 → dropped by scatter
    bins = jnp.zeros(num_classes * num_classes, dtype=jnp.int32).at[unique_mapping].add(
        valid.astype(jnp.int32), mode="drop"
    )
    confmat = bins.reshape(num_classes, num_classes)
    tp = jnp.diag(confmat)
    fp = confmat.sum(0) - tp
    fn = confmat.sum(1) - tp
    tn = confmat.sum() - (fp + fn + tp)
    return tp, fp, tn, fn


def _multiclass_stat_scores_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
) -> Array:
    """Stack + apply average strategy (reference ``stat_scores.py:414-439``)."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_axis = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(axis=sum_axis) if res.ndim > 1 else res
    if average == "macro":
        return res.astype(jnp.float32).mean(axis=sum_axis)
    if average == "weighted":
        weight = (tp + fn).astype(jnp.float32)
        # zero total support (every class empty — e.g. all targets ignore_index)
        # must yield the documented zero score, not a NaN weight vector
        if multidim_average == "global":
            return (res * _safe_divide(weight, weight.sum()).reshape(*weight.shape, 1)).sum(axis=sum_axis)
        return (res * _safe_divide(weight, weight.sum(-1, keepdims=True)).reshape(*weight.shape, 1)).sum(
            axis=sum_axis
        )
    if average is None or average == "none":
        return res
    return None


def multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for multiclass tasks (reference ``stat_scores.py:442-552``)."""
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_format_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


def _multiclass_stat_scores_format_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int,
    average: Optional[str],
    multidim_average: str,
    ignore_index: Optional[int],
) -> Tuple[Array, Array, Array, Array]:
    """Fused format + update.

    On TPU, 2-D float logits with top-1/global accumulation take the single-pass
    one-hot-matmul reduction (``ops/stat_counts.py``: argmax + two MXU matmuls with
    lazily generated one-hot operands — measured 122.7 -> 46.6 µs vs the staged
    argmax -> confusion-matrix pipeline at 8192x1000 on TPU v5e, ~88% of the
    one-pass HBM floor); every other configuration runs the staged stages with
    identical results. Micro averaging reduces the per-class counts (elementwise
    sums equal the direct micro counters exactly).
    """
    from torchmetrics_tpu.ops.stat_counts import (
        fused_multiclass_stat_scores,
        fused_multiclass_stat_scores_supported,
    )

    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if fused_multiclass_stat_scores_supported(preds, target, num_classes, top_k, multidim_average):
        tp, fp, tn, fn = fused_multiclass_stat_scores(preds, target, num_classes, ignore_index)
        if average == "micro":
            return tp.sum(), fp.sum(), tn.sum(), fn.sum()
        return tp, fp, tn, fn
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    return _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )


# --------------------------------------------------------------------------- multilabel


def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Reference ``stat_scores.py:555-583``."""
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float, but got {threshold}.")
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multilabel_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_labels: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Host-side checks (reference ``stat_scores.py:586-632``)."""
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            "Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )
    unique_values = np.unique(np.asarray(target))
    allowed = {0, 1} if ignore_index is None else {0, 1, ignore_index}
    if not set(unique_values.tolist()).issubset(allowed):
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {sorted(allowed)}."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if not set(unique_values.tolist()).issubset({0, 1}):
            raise RuntimeError(
                f"Detected the following values in `preds`: {unique_values} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError("Expected input to be atleast 3D when multidim_average is set to `samplewise`")


def _multilabel_stat_scores_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    """To label format (reference ``stat_scores.py:635-656``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if _is_floating(preds):
        preds = (_sigmoid_if_logits(preds) > threshold).astype(jnp.int32)
    preds = preds.reshape(*preds.shape[:2], -1)
    target = target.reshape(*target.shape[:2], -1)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _multilabel_stat_scores_update(
    preds: Array, target: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    """Reference ``stat_scores.py:659-668``."""
    sum_axis = (0, -1) if multidim_average == "global" else (-1,)
    return _count_stats(preds, target, sum_axis)


def _multilabel_stat_scores_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
) -> Array:
    """Reference ``stat_scores.py:671-694``."""
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_axis = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(axis=sum_axis)
    if average == "macro":
        return res.astype(jnp.float32).mean(axis=sum_axis)
    if average == "weighted":
        w = (tp + fn).astype(jnp.float32)
        return (res * (w / w.sum()).reshape(*w.shape, 1)).sum(axis=sum_axis)
    if average is None or average == "none":
        return res
    return None


def multilabel_stat_scores(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for multilabel tasks (reference ``stat_scores.py:697-802``)."""
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ------------------------------------------------------------------- shared pipelines
# The whole StatScores-derived family (accuracy / precision / recall / f-beta /
# specificity / hamming / ...) differs only in its final reduce. These pipelines give
# each family a one-line validate→format→update stage (the reference repeats this
# plumbing per family; factoring it out is a deliberate divergence).


def _binary_stat_scores_pipeline(
    preds: Array,
    target: Array,
    threshold: float,
    multidim_average: str,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Tuple[Array, Array, Array, Array]:
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    return _binary_stat_scores_update(preds, target, multidim_average)


def _multiclass_stat_scores_pipeline(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str],
    top_k: int,
    multidim_average: str,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Tuple[Array, Array, Array, Array]:
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    return _multiclass_stat_scores_format_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )


def _multilabel_stat_scores_pipeline(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float,
    average: Optional[str],
    multidim_average: str,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Tuple[Array, Array, Array, Array]:
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    return _multilabel_stat_scores_update(preds, target, multidim_average)


def stat_scores(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing wrapper (reference ``stat_scores.py:805-...``)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_stat_scores(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
