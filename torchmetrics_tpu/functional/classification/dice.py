"""Dice score (reference ``functional/classification/dice.py`` — legacy-format metric).

Dice = 2·tp / (2·tp + fp + fn). Supports the legacy input auto-formats the reference
routes through ``_input_format_classification`` (labels, probabilities + threshold,
logits + argmax) for binary and multiclass inputs, with
``average ∈ {micro, macro, weighted, none, samples}`` and
``mdmc_average ∈ {None, global, samplewise}``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.stat_scores import _is_floating, _sigmoid_if_logits
from torchmetrics_tpu.utilities.compute import _safe_divide

Array = jax.Array


def _dice_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
) -> Tuple[Array, Array]:
    """Legacy auto-format to one-hot (N, C, [X]) masks (≙ ``_input_format_classification``)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1:
        # (N, C, ...) scores vs (N, ...) labels
        num_classes = preds.shape[1]
        if top_k is not None and top_k > 1:
            from torchmetrics_tpu.utilities.data import select_topk

            preds_oh = select_topk(preds, topk=top_k, dim=1)
        else:
            preds_oh = jax.nn.one_hot(jnp.argmax(preds, axis=1), num_classes, dtype=jnp.int32)
            preds_oh = jnp.moveaxis(preds_oh, -1, 1)
        target_oh = jnp.moveaxis(jax.nn.one_hot(target, num_classes, dtype=jnp.int32), -1, 1)
        return preds_oh, target_oh
    if _is_floating(preds):
        # same-shape probabilities/logits → binary masks
        preds = (_sigmoid_if_logits(preds) > threshold).astype(jnp.int32)
    if num_classes is not None and num_classes > 1 and preds.ndim == target.ndim and not _is_floating(preds):
        mx = max(int(preds.max()) if preds.size else 0, int(target.max()) if target.size else 0)
        if mx > 1 or num_classes > 2:
            preds_oh = jnp.moveaxis(jax.nn.one_hot(preds, num_classes, dtype=jnp.int32), -1, 1)
            target_oh = jnp.moveaxis(jax.nn.one_hot(target, num_classes, dtype=jnp.int32), -1, 1)
            return preds_oh, target_oh
    # binary labels: treat as 2-class one-hot over {0,1} → stack [1-x, x]
    preds_2 = jnp.stack([1 - preds, preds], axis=1)
    target_2 = jnp.stack([1 - target, target], axis=1)
    return preds_2.astype(jnp.int32), target_2.astype(jnp.int32)


def _dice_update(
    preds_oh: Array,
    target_oh: Array,
    ignore_index: Optional[int] = None,
    mdmc_average: Optional[str] = None,
) -> Tuple[Array, Array, Array]:
    """Per-class (or per-sample-per-class) tp/fp/fn counts."""
    if ignore_index is not None and 0 <= ignore_index < target_oh.shape[1]:
        mask = jnp.ones(target_oh.shape[1], dtype=jnp.int32).at[ignore_index].set(0)
        shape = [1, -1] + [1] * (target_oh.ndim - 2)
        preds_oh = preds_oh * mask.reshape(shape)
        target_oh = target_oh * mask.reshape(shape)
    if mdmc_average == "samplewise" and preds_oh.ndim > 2:
        axes = tuple(range(2, preds_oh.ndim))  # keep (N, C)
    else:
        preds_oh = preds_oh.reshape(preds_oh.shape[0], preds_oh.shape[1], -1)
        target_oh = target_oh.reshape(target_oh.shape[0], target_oh.shape[1], -1)
        axes = (0, 2)
    tp = jnp.sum((preds_oh == 1) & (target_oh == 1), axis=axes)
    fp = jnp.sum((preds_oh == 1) & (target_oh == 0), axis=axes)
    fn = jnp.sum((preds_oh == 0) & (target_oh == 1), axis=axes)
    return tp, fp, fn


def _dice_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str] = "micro",
    zero_division: float = 0.0,
) -> Array:
    """Reduce tp/fp/fn into a dice score (reference ``dice.py:24-70``)."""
    if average == "micro":
        tp, fp, fn = tp.sum(), fp.sum(), fn.sum()
        return _safe_divide(2 * tp, 2 * tp + fp + fn, zero_division)
    score = _safe_divide(2 * tp, 2 * tp + fp + fn, zero_division)
    if average in (None, "none"):
        return score
    if average == "samples":
        # per-sample micro over the class axis
        return _safe_divide(2 * tp.sum(-1), 2 * tp.sum(-1) + fp.sum(-1) + fn.sum(-1), zero_division).mean()
    if average == "weighted":
        weights = (tp + fn).astype(jnp.float32)
        return jnp.sum(score * _safe_divide(weights, weights.sum()))
    if average == "macro":
        present = (tp + fp + fn) > 0
        return jnp.sum(jnp.where(present, score, 0.0)) / jnp.maximum(jnp.sum(present), 1)
    raise ValueError(f"Unsupported average: {average}")


def dice(
    preds: Array,
    target: Array,
    zero_division: float = 0.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Dice score (reference ``dice.py:73-...``)."""
    allowed = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed:
        raise ValueError(f"The `average` has to be one of {allowed}, got {average}.")
    preds_oh, target_oh = _dice_format(preds, target, threshold, top_k, num_classes)
    samplewise = mdmc_average == "samplewise" or average == "samples"
    tp, fp, fn = _dice_update(preds_oh, target_oh, ignore_index, "samplewise" if samplewise else None)
    if mdmc_average == "samplewise" and average != "samples":
        per_sample = _safe_divide(2 * tp.sum(-1), 2 * tp.sum(-1) + fp.sum(-1) + fn.sum(-1), zero_division)
        return per_sample.mean()
    return _dice_compute(tp, fp, fn, average=average, zero_division=zero_division)
