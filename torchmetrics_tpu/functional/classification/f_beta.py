"""F-beta / F1 — binary / multiclass / multilabel (+ task routers).

Capability parity: reference ``functional/classification/f_beta.py`` (reduce ``:38-58``).
"""

from __future__ import annotations

from typing import Optional

import jax

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_pipeline,
)
from torchmetrics_tpu.utilities.compute import _adjust_weights_safe_divide, _safe_divide, _sum_axis
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _fbeta_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reference ``f_beta.py:38-58``."""
    beta2 = beta**2
    if average == "binary":
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        tp = _sum_axis(tp, axis)
        fn = _sum_axis(fn, axis)
        fp = _sum_axis(fp, axis)
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    fbeta_score = _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    return _adjust_weights_safe_divide(fbeta_score, average, multilabel, tp, fp, fn)


def _validate_beta(beta: float) -> None:
    if not (isinstance(beta, float) and beta > 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")


def binary_fbeta_score(
    preds: Array,
    target: Array,
    beta: float,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """F-beta for binary tasks (reference ``f_beta.py:73-...``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.f_beta import binary_fbeta_score
        >>> print(round(float(binary_fbeta_score(preds, target, beta=1.0)), 4))
        0.6667
    """
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _fbeta_reduce(tp, fp, tn, fn, beta, average="binary", multidim_average=multidim_average)


def multiclass_fbeta_score(
    preds: Array,
    target: Array,
    beta: float,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """F-beta for multiclass tasks (reference ``f_beta.py``)."""
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average)


def multilabel_fbeta_score(
    preds: Array,
    target: Array,
    beta: float,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """F-beta for multilabel tasks (reference ``f_beta.py``)."""
    if validate_args:
        _validate_beta(beta)
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average, multilabel=True)


def binary_f1_score(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """F1 for binary tasks (reference ``f_beta.py``)."""
    return binary_fbeta_score(preds, target, 1.0, threshold, multidim_average, ignore_index, validate_args)


def multiclass_f1_score(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """F1 for multiclass tasks (reference ``f_beta.py``)."""
    return multiclass_fbeta_score(
        preds, target, 1.0, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )


def multilabel_f1_score(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """F1 for multilabel tasks (reference ``f_beta.py``)."""
    return multilabel_fbeta_score(
        preds, target, 1.0, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )


def fbeta_score(
    preds: Array,
    target: Array,
    task: str,
    beta: float = 1.0,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing F-beta (reference ``f_beta.py`` legacy API)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_fbeta_score(preds, target, beta, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_fbeta_score(
            preds, target, beta, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_fbeta_score(
            preds, target, beta, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")


def f1_score(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: str = "global",
    top_k: int = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task-routing F1 (reference ``f_beta.py`` legacy API)."""
    return fbeta_score(
        preds, target, task, 1.0, threshold, num_classes, num_labels,
        average, multidim_average, top_k, ignore_index, validate_args,
    )
