"""Precision at fixed recall (reference ``functional/classification/precision_fixed_recall.py``).

The mirror image of ``recall_fixed_precision.py``: same curve states, the selection
swaps the objective and the constrained coordinate.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax

from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_compute,
    _binary_recall_at_fixed_precision_arg_validation,
    _lexi_max_at_constraint,
    _multiclass_recall_at_fixed_precision_arg_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
)
from torchmetrics_tpu.utilities.enums import ClassificationTask

Array = jax.Array


def _precision_at_recall(
    precision: Array, recall: Array, thresholds: Array, min_recall: float
) -> Tuple[Array, Array]:
    """Highest precision whose recall clears the floor (reference ``precision_fixed_recall.py:42-61``)."""
    return _lexi_max_at_constraint(precision, recall, thresholds, min_recall)


def binary_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    r"""Highest precision given a minimum recall floor, binary task (reference ``:63-134``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.75, 0.05, 0.35, 0.75, 0.05, 0.65])
        >>> target = jnp.asarray([1, 0, 1, 1, 0, 0])
        >>> from torchmetrics_tpu.functional.classification.precision_fixed_recall import binary_precision_at_fixed_recall
        >>> print(tuple(round(float(v), 4) for v in binary_precision_at_fixed_recall(preds, target, min_recall=0.5)))
        (1.0, 0.75)
    """
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_recall, thresholds, ignore_index, arg_name="min_recall")
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_recall_at_fixed_precision_compute(state, thresholds, min_recall, reduce_fn=_precision_at_recall)


def multiclass_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_classes: int,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    r"""Highest per-class precision given a minimum recall floor (reference ``:137-219``)."""
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_recall, thresholds, ignore_index, arg_name="min_recall")
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_recall_at_fixed_precision_arg_compute(
        state, num_classes, thresholds, min_recall, reduce_fn=_precision_at_recall
    )


def multilabel_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_labels: int,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    r"""Highest per-label precision given a minimum recall floor (reference ``:222-303``)."""
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_recall, thresholds, ignore_index, arg_name="min_recall")
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_recall_at_fixed_precision_arg_compute(
        state, num_labels, thresholds, ignore_index, min_recall, reduce_fn=_precision_at_recall
    )


def precision_at_fixed_recall(
    preds: Array,
    target: Array,
    task: str,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task-routing wrapper (reference ``:306-348``)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_at_fixed_recall(preds, target, min_recall, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_at_fixed_recall(
            preds, target, num_classes, min_recall, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_at_fixed_recall(
            preds, target, num_labels, min_recall, thresholds, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
