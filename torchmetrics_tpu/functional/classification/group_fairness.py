"""Group fairness metrics (reference ``functional/classification/group_fairness.py``).

TPU-first: the reference sorts by group and splits into ragged per-group tensors
(``group_fairness.py:51-81``); here the per-group tp/fp/tn/fn are one **vectorized
masked count** over a fixed ``num_groups`` axis — static shapes, single fused graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
)
from torchmetrics_tpu.utilities.compute import _safe_divide

Array = jax.Array


def _groups_validation(groups: Array, num_groups: int) -> None:
    """Reference ``group_fairness.py:29-43``."""
    if np.asarray(groups).max() > num_groups - 1 or np.asarray(groups).min() < 0:
        raise ValueError(f"The largest number in the groups tensor is {int(np.asarray(groups).max())}, which is larger than the specified number of groups {num_groups}.")
    if not jnp.issubdtype(jnp.asarray(groups).dtype, jnp.integer):
        raise ValueError(f"Excepted groups to be of integer type but got {groups.dtype}")


def _groups_format(groups: Array) -> Array:
    """Reference ``group_fairness.py:46-48``."""
    return jnp.asarray(groups).reshape(groups.shape[0], -1)


def _binary_groups_stat_scores(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> List[Tuple[Array, Array, Array, Array]]:
    """Per-group tp/fp/tn/fn via masked counts (reference sorts+splits, ``:51-81``)."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    groups = _groups_format(groups)

    g = groups.flatten()
    p = preds.flatten()
    t = target.flatten()
    group_oh = jax.nn.one_hot(g, num_groups, dtype=jnp.int32).T  # (G, N)
    tp = group_oh @ ((t == p) & (t == 1)).astype(jnp.int32)
    fn = group_oh @ ((t != p) & (t == 1)).astype(jnp.int32)
    fp = group_oh @ ((t != p) & (t == 0)).astype(jnp.int32)
    tn = group_oh @ ((t == p) & (t == 0)).astype(jnp.int32)
    return [(tp[i], fp[i], tn[i], fn[i]) for i in range(num_groups)]


def _groups_reduce(
    group_stats: List[Tuple[Array, Array, Array, Array]]
) -> Dict[str, Array]:
    """Rates per group (reference ``group_fairness.py:84-88``).

    A group with no observed samples has all-zero stats; its rates are the
    documented zeros, not 0/0 NaN (which would poison every downstream
    min/max-rate comparison).
    """
    return {
        f"group_{group}": _safe_divide(jnp.stack(stats), jnp.stack(stats).sum())
        for group, stats in enumerate(group_stats)
    }


def _groups_stat_transform(
    group_stats: List[Tuple[Array, Array, Array, Array]]
) -> Dict[str, Array]:
    """Stack per-statistic tensors (reference ``group_fairness.py:91-100``)."""
    return {
        "tp": jnp.stack([s[0] for s in group_stats]),
        "fp": jnp.stack([s[1] for s in group_stats]),
        "tn": jnp.stack([s[2] for s in group_stats]),
        "fn": jnp.stack([s[3] for s in group_stats]),
    }


def binary_groups_stat_rates(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Per-group tp/fp/tn/fn rates (reference ``group_fairness.py:103-158``)."""
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    return _groups_reduce(group_stats)


def _compute_binary_demographic_parity(tp: Array, fp: Array, tn: Array, fn: Array) -> Dict[str, Array]:
    """Reference ``group_fairness.py:161-171``."""
    pos_rates = _safe_divide(tp + fp, tp + fp + tn + fn)
    min_id = int(jnp.argmin(pos_rates))
    max_id = int(jnp.argmax(pos_rates))
    return {f"DP_{min_id}_{max_id}": _safe_divide(pos_rates[min_id], pos_rates[max_id])}


def demographic_parity(
    preds: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """min/max positivity-rate ratio across groups (reference ``group_fairness.py:174-237``)."""
    num_groups = len(np.unique(np.asarray(groups)))
    target = jnp.zeros(jnp.asarray(preds).shape, dtype=jnp.int32)
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    return _compute_binary_demographic_parity(**_groups_stat_transform(group_stats))


def _compute_binary_equal_opportunity(tp: Array, fp: Array, tn: Array, fn: Array) -> Dict[str, Array]:
    """Reference ``group_fairness.py:239-251``."""
    tprs = _safe_divide(tp, tp + fn)
    min_id = int(jnp.argmin(tprs))
    max_id = int(jnp.argmax(tprs))
    return {f"EO_{min_id}_{max_id}": _safe_divide(tprs[min_id], tprs[max_id])}


def equal_opportunity(
    preds: Array,
    target: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """min/max TPR ratio across groups (reference ``group_fairness.py:254-319``)."""
    num_groups = len(np.unique(np.asarray(groups)))
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    return _compute_binary_equal_opportunity(**_groups_stat_transform(group_stats))


def binary_fairness(
    preds: Array,
    target: Array,
    groups: Array,
    task: str = "all",
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic parity and/or equal opportunity (reference ``group_fairness.py:322-...``)."""
    if task not in ["demographic_parity", "equal_opportunity", "all"]:
        raise ValueError(
            f"Expected argument `task` to either be ``demographic_parity``,"
            f"``equal_opportunity`` or ``all`` but got {task}."
        )
    num_groups = len(np.unique(np.asarray(groups)))
    if task == "demographic_parity":
        target = jnp.zeros(jnp.asarray(preds).shape, dtype=jnp.int32)
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    transformed = _groups_stat_transform(group_stats)
    out: Dict[str, Array] = {}
    if task in ("demographic_parity", "all"):
        out.update(_compute_binary_demographic_parity(**transformed))
    if task in ("equal_opportunity", "all"):
        out.update(_compute_binary_equal_opportunity(**transformed))
    return out
