"""intersection_over_union (reference ``functional/detection/iou.py``) — jnp kernel, no torchvision."""

from torchmetrics_tpu.functional.detection._iou_variants import intersection_over_union

__all__ = ["intersection_over_union"]
