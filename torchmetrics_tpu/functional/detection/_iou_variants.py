"""Shared machinery for the four box-overlap functionals.

The reference ships four near-identical files (``functional/detection/{iou,giou,diou,
ciou}.py``), each deferring to a torchvision op. Here one factory builds all four from
the jnp pairwise kernels in ``helpers.py``; thresholding uses ``jnp.where`` so the
public functions stay jit-safe (no boolean indexing).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.detection.helpers import _box_ciou, _box_diou, _box_giou, _box_iou

Array = jax.Array


def _variant_update(
    kernel: Callable[[Array, Array], Array],
    preds: Array,
    target: Array,
    iou_threshold: Optional[float],
    replacement_val: float = 0,
) -> Array:
    """Pairwise score matrix with sub-threshold entries replaced (reference ``iou.py:29-35``)."""
    scores = kernel(jnp.asarray(preds, dtype=jnp.float32), jnp.asarray(target, dtype=jnp.float32))
    if iou_threshold is not None:
        scores = jnp.where(scores < iou_threshold, replacement_val, scores)
    return scores


def _variant_compute(scores: Array, labels_eq: bool = True) -> Array:
    """Mean of the matched diagonal — or of all pairs when labels differ (reference ``iou.py:38-41``)."""
    if labels_eq:
        return jnp.diagonal(scores).mean()
    return scores.mean()


def _make_variant(kernel: Callable[[Array, Array], Array], public_name: str) -> Callable:
    def fn(
        preds: Array,
        target: Array,
        iou_threshold: Optional[float] = None,
        replacement_val: float = 0,
        aggregate: bool = True,
    ) -> Array:
        scores = _variant_update(kernel, preds, target, iou_threshold, replacement_val)
        return _variant_compute(scores) if aggregate else scores

    fn.__name__ = public_name
    fn.__qualname__ = public_name
    fn.__doc__ = (
        f"Compute ``{public_name}`` between two sets of xyxy boxes.\n\n"
        "Args:\n"
        "    preds: ``(N, 4)`` predicted boxes, ``(x1, y1, x2, y2)`` with ``x1 < x2``, ``y1 < y2``.\n"
        "    target: ``(M, 4)`` ground-truth boxes in the same layout.\n"
        "    iou_threshold: optional floor; entries below it become ``replacement_val``.\n"
        "    replacement_val: value written for sub-threshold pairs.\n"
        "    aggregate: return the matched-pair mean instead of the full ``(N, M)`` matrix.\n\n"
        f"Own jnp kernels (reference ``functional/detection/{public_name.split('_')[0]}``-family "
        "delegates to torchvision; see ``helpers.py`` here)."
    )
    return fn


intersection_over_union = _make_variant(_box_iou, "intersection_over_union")
generalized_intersection_over_union = _make_variant(_box_giou, "generalized_intersection_over_union")
distance_intersection_over_union = _make_variant(_box_diou, "distance_intersection_over_union")
complete_intersection_over_union = _make_variant(_box_ciou, "complete_intersection_over_union")
