"""distance_intersection_over_union (reference ``functional/detection/diou.py``) — jnp kernel, no torchvision."""

from torchmetrics_tpu.functional.detection._iou_variants import distance_intersection_over_union

__all__ = ["distance_intersection_over_union"]
