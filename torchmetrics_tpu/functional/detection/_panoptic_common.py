"""Panoptic-quality machinery (reference ``functional/detection/_panoptic_quality_common.py``).

Design: the reference builds Python dicts keyed by ``(category_id, instance_id)``
"colors" and loops over them. Here every pixel's color is packed into one integer key
(``cat * stride + inst``) so segment areas and pairwise intersections come out of a
single vectorized ``np.unique(..., return_counts=True)`` pass on host — the only loops
left run over unique intersection pairs (tens, not pixels). Per-sample stats fold into
dense per-category arrays that live as ordinary sum states on device.
"""

from __future__ import annotations

from typing import Collection, Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _parse_categories(things: Collection[int], stuffs: Collection[int]) -> Tuple[Set[int], Set[int]]:
    """Validate and dedupe the things/stuffs category sets (reference ``:151-181``)."""
    things_parsed = set(things)
    if len(things_parsed) < len(things):
        rank_zero_warn("The provided `things` categories contained duplicates, which have been removed.", UserWarning)
    stuffs_parsed = set(stuffs)
    if len(stuffs_parsed) < len(stuffs):
        rank_zero_warn("The provided `stuffs` categories contained duplicates, which have been removed.", UserWarning)
    if not all(isinstance(val, (int, np.integer)) for val in things_parsed):
        raise TypeError(f"Expected argument `things` to contain `int` categories, but got {things}")
    if not all(isinstance(val, (int, np.integer)) for val in stuffs_parsed):
        raise TypeError(f"Expected argument `stuffs` to contain `int` categories, but got {stuffs}")
    if things_parsed & stuffs_parsed:
        raise ValueError(
            f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things} and {stuffs}"
        )
    if not (things_parsed | stuffs_parsed):
        raise ValueError("At least one of `things` and `stuffs` must be non-empty.")
    return things_parsed, stuffs_parsed


def _validate_inputs(preds, target) -> None:
    """Shape/type checks (reference ``:183-208``)."""
    if not isinstance(preds, (jax.Array, np.ndarray)):
        raise TypeError(f"Expected argument `preds` to be an array, but got {type(preds)}")
    if not isinstance(target, (jax.Array, np.ndarray)):
        raise TypeError(f"Expected argument `target` to be an array, but got {type(target)}")
    if preds.shape != target.shape:
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same shape, but got {preds.shape} and {target.shape}"
        )
    if preds.ndim < 3:
        raise ValueError(
            "Expected argument `preds` to have at least one spatial dimension (B, *spatial_dims, 2), "
            f"got {preds.shape}"
        )
    if preds.shape[-1] != 2:
        raise ValueError(
            "Expected argument `preds` to have exactly 2 channels in the last dimension (category, instance), "
            f"got {preds.shape} instead"
        )


def _get_void_color(things: Set[int], stuffs: Set[int]) -> Tuple[int, int]:
    """An unused (category, instance) color (reference ``:210-221``)."""
    return 1 + max([0, *list(things), *list(stuffs)]), 0


def _get_category_id_to_continuous_id(things: Set[int], stuffs: Set[int]) -> Dict[int, int]:
    """Original category IDs -> dense [0, n) ids; things first (reference ``:224-240``)."""
    mapping = {thing_id: idx for idx, thing_id in enumerate(things)}
    mapping.update({stuff_id: idx + len(things) for idx, stuff_id in enumerate(stuffs)})
    return mapping


def _preprocess_inputs(
    things: Set[int],
    stuffs: Set[int],
    inputs,
    void_color: Tuple[int, int],
    allow_unknown_category: bool,
) -> np.ndarray:
    """Flatten spatial dims, zero stuff instance ids, map unknowns to void (reference ``:268-304``)."""
    arr = np.asarray(inputs).astype(np.int64)
    arr = arr.reshape(arr.shape[0], -1, 2).copy()
    cats = arr[..., 0]
    mask_stuffs = np.isin(cats, list(stuffs))
    mask_things = np.isin(cats, list(things))
    arr[..., 1] = np.where(mask_stuffs, 0, arr[..., 1])
    known = mask_things | mask_stuffs
    if not allow_unknown_category and not known.all():
        raise ValueError(f"Unknown categories found: {arr[~known]}")
    arr[~known] = np.asarray(void_color, dtype=np.int64)
    return arr


def _panoptic_stats_sample(
    pred_sample: np.ndarray,
    target_sample: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    stuffs_modified_metric: Optional[Set[int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Per-sample (iou_sum, tp, fp, fn) dense per-category stats (reference ``:307-382``).

    For the modified variant, ``true_positives`` counts target segments for the selected
    stuff classes and ``iou_sum`` accumulates IoU at threshold 0 — identical compute
    formula downstream (reference note at ``:315-319``).
    """
    stuffs_modified_metric = stuffs_modified_metric or set()
    n_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(n_categories)
    true_positives = np.zeros(n_categories, dtype=np.int64)
    false_positives = np.zeros(n_categories, dtype=np.int64)
    false_negatives = np.zeros(n_categories, dtype=np.int64)

    # One vectorized pass. Category/instance ids can be arbitrarily large (COCO packs
    # RGB into instance ids, up to 2^24), so first densify both columns through
    # np.unique inverse codes — packed keys then stay far below int64 overflow.
    n_px = pred_sample.shape[0]
    both = np.concatenate([pred_sample, target_sample, np.asarray([void_color], dtype=np.int64)], axis=0)
    unique_cats, cat_codes = np.unique(both[:, 0], return_inverse=True)
    unique_insts, inst_codes = np.unique(both[:, 1], return_inverse=True)
    stride_inst = len(unique_insts)
    n_keys = len(unique_cats) * stride_inst
    keys = cat_codes * stride_inst + inst_codes

    pred_keys_px = keys[:n_px]
    target_keys_px = keys[n_px : 2 * n_px]
    void_key = int(keys[-1])

    def _key_category(key: int) -> int:
        return int(unique_cats[key // stride_inst])

    pred_colors, pred_counts = np.unique(pred_keys_px, return_counts=True)
    target_colors, target_counts = np.unique(target_keys_px, return_counts=True)
    pair_keys, pair_counts = np.unique(pred_keys_px * n_keys + target_keys_px, return_counts=True)
    pair_pred = pair_keys // n_keys
    pair_target = pair_keys % n_keys

    pred_area = dict(zip(pred_colors.tolist(), pred_counts.tolist()))
    target_area = dict(zip(target_colors.tolist(), target_counts.tolist()))
    inter_area = {
        (int(p), int(t)): int(c) for p, t, c in zip(pair_pred, pair_target, pair_counts)
    }

    pred_matched: Set[int] = set()
    target_matched: Set[int] = set()
    for (p_key, t_key), inter in inter_area.items():
        if t_key == void_key or p_key == void_key:
            continue
        p_cat, t_cat = _key_category(p_key), _key_category(t_key)
        if p_cat != t_cat:
            continue
        pred_void = inter_area.get((p_key, void_key), 0)
        void_target = inter_area.get((void_key, t_key), 0)
        union = pred_area[p_key] - pred_void + target_area[t_key] - void_target - inter
        iou = inter / union if union > 0 else 0.0
        continuous_id = cat_id_to_continuous_id[int(t_cat)]
        if t_cat not in stuffs_modified_metric and iou > 0.5:
            pred_matched.add(p_key)
            target_matched.add(t_key)
            iou_sum[continuous_id] += iou
            true_positives[continuous_id] += 1
        elif t_cat in stuffs_modified_metric and iou > 0:
            iou_sum[continuous_id] += iou

    # FN: unmatched target segments not mostly covered by void in the prediction.
    for t_key, area in target_area.items():
        if t_key == void_key or t_key in target_matched:
            continue
        cat = _key_category(t_key)
        if cat in stuffs_modified_metric:
            continue
        if inter_area.get((void_key, t_key), 0) / area <= 0.5:
            false_negatives[cat_id_to_continuous_id[cat]] += 1

    # FP: unmatched predicted segments not mostly void in the target.
    for p_key, area in pred_area.items():
        if p_key == void_key or p_key in pred_matched:
            continue
        cat = _key_category(p_key)
        if cat in stuffs_modified_metric:
            continue
        if inter_area.get((p_key, void_key), 0) / area <= 0.5:
            false_positives[cat_id_to_continuous_id[cat]] += 1

    # Modified variant: each target segment of a selected stuff class counts once.
    for t_key in target_area:
        if t_key == void_key:
            continue
        cat = _key_category(t_key)
        if cat in stuffs_modified_metric:
            true_positives[cat_id_to_continuous_id[cat]] += 1

    return iou_sum, true_positives, false_positives, false_negatives


def _panoptic_quality_update(
    flatten_preds: np.ndarray,
    flatten_target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    modified_metric_stuffs: Optional[Set[int]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Batch stats: per-sample matching folded into dense category arrays (reference ``:385-436``)."""
    n_categories = len(cat_id_to_continuous_id)
    iou_sum = np.zeros(n_categories)
    true_positives = np.zeros(n_categories, dtype=np.int64)
    false_positives = np.zeros(n_categories, dtype=np.int64)
    false_negatives = np.zeros(n_categories, dtype=np.int64)
    for pred_sample, target_sample in zip(flatten_preds, flatten_target):
        result = _panoptic_stats_sample(
            pred_sample, target_sample, cat_id_to_continuous_id, void_color, modified_metric_stuffs
        )
        iou_sum += result[0]
        true_positives += result[1]
        false_positives += result[2]
        false_negatives += result[3]
    return (
        jnp.asarray(iou_sum),
        jnp.asarray(true_positives),
        jnp.asarray(false_positives),
        jnp.asarray(false_negatives),
    )


def _panoptic_quality_compute(
    iou_sum: Array,
    true_positives: Array,
    false_positives: Array,
    false_negatives: Array,
) -> Array:
    """``mean_cat( IoU_sum / (TP + FP/2 + FN/2) )`` over seen categories (reference ``:439-462``)."""
    denominator = true_positives + 0.5 * false_positives + 0.5 * false_negatives
    per_category = jnp.where(denominator > 0, iou_sum / jnp.where(denominator > 0, denominator, 1.0), 0.0)
    seen = denominator > 0
    n_seen = jnp.sum(seen)
    return jnp.sum(jnp.where(seen, per_category, 0.0)) / jnp.where(n_seen > 0, n_seen, 1)
