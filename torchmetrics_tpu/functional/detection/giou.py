"""generalized_intersection_over_union (reference ``functional/detection/giou.py``) — jnp kernel, no torchvision."""

from torchmetrics_tpu.functional.detection._iou_variants import generalized_intersection_over_union

__all__ = ["generalized_intersection_over_union"]
