"""complete_intersection_over_union (reference ``functional/detection/ciou.py``) — jnp kernel, no torchvision."""

from torchmetrics_tpu.functional.detection._iou_variants import complete_intersection_over_union

__all__ = ["complete_intersection_over_union"]
