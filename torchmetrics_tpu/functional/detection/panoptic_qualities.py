"""Panoptic quality functionals (reference ``functional/detection/panoptic_qualities.py``)."""

from __future__ import annotations

from typing import Collection

import jax

from torchmetrics_tpu.functional.detection._panoptic_common import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _preprocess_inputs,
    _validate_inputs,
)

Array = jax.Array


def panoptic_quality(
    preds: Array,
    target: Array,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    r"""Compute Panoptic Quality for panoptic segmentations (reference ``panoptic_qualities.py:30-105``).

    ``PQ = IoU_sum / (TP + 0.5 FP + 0.5 FN)`` per category, averaged over seen categories.

    Args:
        preds: ``(B, *spatial, 2)`` array of ``(category_id, instance_id)`` pairs per pixel.
        target: ground truth of the same shape.
        things: category ids of countable things (instances distinguished).
        stuffs: category ids of uncountable stuffs (instance id ignored).
        allow_unknown_preds_category: map unknown predicted categories to void instead of raising.
    """
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, preds, void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, target, void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(flatten_preds, flatten_target, cat_id_to_continuous_id, void_color)
    return _panoptic_quality_compute(iou_sum, tp, fp, fn)


def modified_panoptic_quality(
    preds: Array,
    target: Array,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    r"""Modified Panoptic Quality: stuff classes scored per-segment at IoU > 0 (reference ``:108-180``).

    Adaptation from the Seamless Scene Segmentation paper where each stuff class
    contributes its summed IoU over target segments rather than 0.5-thresholded matches.
    """
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, preds, void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, target, void_color, True)
    iou_sum, tp, fp, fn = _panoptic_quality_update(
        flatten_preds, flatten_target, cat_id_to_continuous_id, void_color, modified_metric_stuffs=stuffs
    )
    return _panoptic_quality_compute(iou_sum, tp, fp, fn)
