"""Box kernels for the detection domain.

TPU-first replacements for the torchvision ops the reference leans on
(``box_iou``/``box_convert``/``generalized_box_iou``/``distance_box_iou``/
``complete_box_iou``; reference ``functional/detection/iou.py:20-26`` and
``detection/mean_ap.py:32``). Everything here is pure ``jnp`` broadcasting over an
``(N, 4) x (M, 4) -> (N, M)`` grid — no data-dependent control flow, so the kernels jit
and vmap cleanly and fuse into surrounding XLA graphs.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_EPS = 1e-7  # matches torchvision's eps in distance/complete IoU denominators

_ALLOWED_BOX_FORMATS = ("xyxy", "xywh", "cxcywh")


def _box_convert(boxes: Array, in_fmt: str, out_fmt: str) -> Array:
    """Convert ``(N, 4)`` boxes between xyxy / xywh / cxcywh layouts.

    Own implementation of torchvision ``box_convert`` (used by the reference at
    ``detection/mean_ap.py:398``).
    """
    if in_fmt not in _ALLOWED_BOX_FORMATS or out_fmt not in _ALLOWED_BOX_FORMATS:
        raise ValueError(f"Box formats must be one of {_ALLOWED_BOX_FORMATS}, got {in_fmt} -> {out_fmt}")
    if in_fmt == out_fmt:
        return boxes
    a, b, c, d = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    if in_fmt == "xywh":
        x1, y1, x2, y2 = a, b, a + c, b + d
    elif in_fmt == "cxcywh":
        x1, y1, x2, y2 = a - c / 2, b - d / 2, a + c / 2, b + d / 2
    else:
        x1, y1, x2, y2 = a, b, c, d
    if out_fmt == "xyxy":
        out = (x1, y1, x2, y2)
    elif out_fmt == "xywh":
        out = (x1, y1, x2 - x1, y2 - y1)
    else:
        out = ((x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1)
    return jnp.stack(out, axis=-1)


def _box_area(boxes: Array) -> Array:
    """Area of ``(N, 4)`` xyxy boxes."""
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _box_inter_union(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Pairwise intersection and union matrices for xyxy boxes."""
    area1 = _box_area(preds)
    area2 = _box_area(target)
    lt = jnp.maximum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.minimum(preds[:, None, 2:], target[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return inter, union


def _box_iou(preds: Array, target: Array) -> Array:
    """Pairwise IoU matrix ``(N, M)`` for xyxy boxes."""
    inter, union = _box_inter_union(preds, target)
    return inter / jnp.where(union == 0, 1.0, union)


def _enclosing_box(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Width/height of the smallest box enclosing each pred/target pair."""
    lt = jnp.minimum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.maximum(preds[:, None, 2:], target[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    return wh[..., 0], wh[..., 1]


def _box_giou(preds: Array, target: Array) -> Array:
    """Pairwise generalized IoU: ``iou - (enclose - union) / enclose``."""
    inter, union = _box_inter_union(preds, target)
    iou = inter / jnp.where(union == 0, 1.0, union)
    ew, eh = _enclosing_box(preds, target)
    enclose = ew * eh
    return iou - (enclose - union) / jnp.where(enclose == 0, 1.0, enclose)


def _center_distance_sq(preds: Array, target: Array) -> Array:
    """Squared distance between box centers, pairwise."""
    cp = (preds[:, None, :2] + preds[:, None, 2:]) / 2
    ct = (target[None, :, :2] + target[None, :, 2:]) / 2
    diff = cp - ct
    return diff[..., 0] ** 2 + diff[..., 1] ** 2


def _box_diou(preds: Array, target: Array) -> Array:
    """Pairwise distance IoU: ``iou - d^2 / c^2`` (c = enclosing-box diagonal)."""
    iou = _box_iou(preds, target)
    ew, eh = _enclosing_box(preds, target)
    diag_sq = ew**2 + eh**2 + _EPS
    return iou - _center_distance_sq(preds, target) / diag_sq


def _box_ciou(preds: Array, target: Array) -> Array:
    """Pairwise complete IoU: dIoU minus the aspect-ratio consistency term."""
    iou = _box_iou(preds, target)
    ew, eh = _enclosing_box(preds, target)
    diag_sq = ew**2 + eh**2 + _EPS
    dist_term = _center_distance_sq(preds, target) / diag_sq

    wp = preds[:, 2] - preds[:, 0]
    hp = preds[:, 3] - preds[:, 1]
    wt = target[:, 2] - target[:, 0]
    ht = target[:, 3] - target[:, 1]
    v = (4 / jnp.pi**2) * (
        jnp.arctan(wt / (ht + _EPS))[None, :] - jnp.arctan(wp / (hp + _EPS))[:, None]
    ) ** 2
    alpha = v / (1 - iou + v + _EPS)
    return iou - dist_term - alpha * v
