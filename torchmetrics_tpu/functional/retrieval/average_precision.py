"""Retrieval average precision (reference ``functional/retrieval/average_precision.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_average_precision(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """AP over a single query: mean over relevant top-k docs of (j / rank_j).

    Branch-free form: with documents sorted by score, ``j = cumsum(rel)`` and the sum of
    ``rel * j / rank`` divided by the number of relevant retrieved docs equals the
    reference's loop over relevant positions (``average_precision.py:22-60``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.average_precision import retrieval_average_precision
        >>> print(round(float(retrieval_average_precision(preds, target)), 4))
        0.8333
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    top_k = preds.shape[-1] if top_k is None else top_k
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError(f"Argument ``top_k`` has to be a positive integer or None, but got {top_k}.")

    k = min(top_k, preds.shape[-1])
    order = jnp.argsort(-preds)
    rel = target[order][:k].astype(jnp.float32)
    ranks = jnp.arange(1, k + 1, dtype=jnp.float32)
    j = jnp.cumsum(rel)
    n_rel = rel.sum()
    ap = jnp.sum(rel * j / ranks) / jnp.where(n_rel == 0, 1.0, n_rel)
    return jnp.where(n_rel == 0, 0.0, ap)
