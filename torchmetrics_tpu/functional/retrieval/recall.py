"""Retrieval recall@k (reference ``functional/retrieval/recall.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_recall(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Fraction of the relevant documents retrieved in the top k (reference ``recall.py:22-58``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.recall import retrieval_recall
        >>> print(round(float(retrieval_recall(preds, target)), 4))
        1.0
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    if top_k is None:
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")

    n_pos = target.sum()
    relevant = target[jnp.argsort(-preds)][:top_k].sum().astype(jnp.float32)
    return jnp.where(n_pos == 0, 0.0, relevant / jnp.where(n_pos == 0, 1, n_pos))
