"""Retrieval R-precision (reference ``functional/retrieval/r_precision.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """Precision at the R-th position, R = number of relevant docs (reference ``r_precision.py:22-55``).

    Branch-free: with docs sorted by score, the count of relevant docs in the first R
    slots is ``sum(rel * (rank <= R))`` — no dynamic slicing by a traced R.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.r_precision import retrieval_r_precision
        >>> print(round(float(retrieval_r_precision(preds, target)), 4))
        0.5
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    rel = target[jnp.argsort(-preds)].astype(jnp.float32)
    n_rel = rel.sum()
    ranks = jnp.arange(1, rel.shape[-1] + 1)
    in_first_r = (ranks <= n_rel).astype(jnp.float32)
    hit = jnp.sum(rel * in_first_r)
    return jnp.where(n_rel == 0, 0.0, hit / jnp.where(n_rel == 0, 1.0, n_rel))
