"""Retrieval precision-recall curve (reference ``functional/retrieval/precision_recall_curve.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_precision_recall_curve(
    preds: Array, target: Array, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Precision@k and recall@k for every k in [1, max_k] (reference ``:24-120``)."""
    preds, target = _check_retrieval_functional_inputs(preds, target)

    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if max_k is None:
        max_k = preds.shape[-1]
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")

    n = preds.shape[-1]
    if adaptive_k and max_k > n:
        topk = jnp.concatenate([jnp.arange(1, n + 1), jnp.full((max_k - n,), n)])
    else:
        topk = jnp.arange(1, max_k + 1)

    relevant = target[jnp.argsort(-preds)][: min(max_k, n)].astype(jnp.float32)
    relevant = jnp.pad(relevant, (0, max(0, max_k - relevant.shape[0])))
    relevant = jnp.cumsum(relevant)

    n_pos = target.sum()
    recall = jnp.where(n_pos == 0, 0.0, relevant / jnp.where(n_pos == 0, 1, n_pos))
    precision = jnp.where(n_pos == 0, 0.0, relevant / topk)
    return precision, recall, topk
