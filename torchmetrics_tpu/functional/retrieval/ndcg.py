"""Retrieval normalized DCG (reference ``functional/retrieval/ndcg.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def _dcg(target: Array) -> Array:
    """Discounted cumulative gain along the last axis (reference ``ndcg.py:21-24``)."""
    denom = jnp.log2(jnp.arange(target.shape[-1]) + 2.0)
    return (target / denom).sum(axis=-1)


def retrieval_normalized_dcg(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """nDCG over a single query; graded (non-binary) relevance allowed (reference ``ndcg.py:27-74``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg
        >>> print(round(float(retrieval_normalized_dcg(preds, target)), 4))
        0.9197
    """
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)

    top_k = preds.shape[-1] if top_k is None else top_k
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")

    k = min(top_k, preds.shape[-1])
    sorted_target = target[jnp.argsort(-preds)][:k].astype(jnp.float32)
    ideal_target = -jnp.sort(-target.astype(jnp.float32))[:k]

    ideal_dcg = _dcg(ideal_target)
    target_dcg = _dcg(sorted_target)
    return jnp.where(ideal_dcg == 0, 0.0, target_dcg / jnp.where(ideal_dcg == 0, 1.0, ideal_dcg))
