"""Retrieval precision@k (reference ``functional/retrieval/precision.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_precision(
    preds: Array, target: Array, top_k: Optional[int] = None, adaptive_k: bool = False
) -> Array:
    """Fraction of the top-k retrieved documents that are relevant (reference ``precision.py:22-63``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.precision import retrieval_precision
        >>> print(round(float(retrieval_precision(preds, target)), 4))
        0.5
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if top_k is None or (adaptive_k and top_k > preds.shape[-1]):
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")

    order = jnp.argsort(-preds)
    relevant = target[order][: min(top_k, preds.shape[-1])].sum().astype(jnp.float32)
    return jnp.where(target.sum() == 0, 0.0, relevant / top_k)
