"""Retrieval fall-out@k (reference ``functional/retrieval/fall_out.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_fall_out(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Fraction of the non-relevant documents retrieved in the top k (reference ``fall_out.py:22-60``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.fall_out import retrieval_fall_out
        >>> print(round(float(retrieval_fall_out(preds, target)), 4))
        1.0
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    top_k = preds.shape[-1] if top_k is None else top_k
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")

    negative = 1 - target
    n_neg = negative.sum()
    retrieved_neg = negative[jnp.argsort(-preds)][:top_k].sum().astype(jnp.float32)
    return jnp.where(n_neg == 0, 0.0, retrieved_neg / jnp.where(n_neg == 0, 1, n_neg))
