"""Retrieval mean reciprocal rank (reference ``functional/retrieval/reciprocal_rank.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """1 / rank of the first relevant document (reference ``reciprocal_rank.py:22-52``).

    ``argmax`` over the rank-sorted binary relevance returns the first hit — no
    ``nonzero`` host sync.
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    rel = target[jnp.argsort(-preds)]
    first = jnp.argmax(rel)
    return jnp.where(rel.sum() == 0, 0.0, 1.0 / (first + 1.0))
