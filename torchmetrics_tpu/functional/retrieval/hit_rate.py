"""Retrieval hit rate@k (reference ``functional/retrieval/hit_rate.py``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_hit_rate(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """1.0 when any relevant document lands in the top k (reference ``hit_rate.py:22-57``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, True, False, True])
        >>> from torchmetrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate
        >>> print(round(float(retrieval_hit_rate(preds, target)), 4))
        1.0
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)

    if top_k is None:
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")

    relevant = target[jnp.argsort(-preds)][:top_k].sum()
    return (relevant > 0).astype(jnp.float32)
