"""Theil's U (uncertainty coefficient; reference ``functional/nominal/theils_u.py``)."""

from __future__ import annotations

import itertools
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from torchmetrics_tpu.functional.nominal.utils import (
    _drop_empty_rows_and_cols,
    _nominal_bins_update,
    _nominal_dense_update,
    _nominal_input_validation,
)

Array = jax.Array


def _conditional_entropy_compute(confmat: np.ndarray) -> float:
    """H(X|Y) from the contingency table (reference ``theils_u.py:29-52``)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    total = confmat.sum()
    p_xy = confmat / total
    p_y = confmat.sum(1) / total
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = p_xy * np.log(p_y[:, None] / p_xy)
    return float(np.nansum(terms))


def _theils_u_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Fold a batch into the confusion matrix (reference ``theils_u.py:55-77``)."""
    return _nominal_bins_update(
        preds, target, num_classes, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )


def _theils_u_compute(confmat: Array) -> Array:
    """U = (H(X) - H(X|Y)) / H(X) (reference ``theils_u.py:80-103``)."""
    cm = _drop_empty_rows_and_cols(np.asarray(confmat, dtype=np.float64))
    s_xy = _conditional_entropy_compute(cm)
    total = cm.sum()
    p_x = cm.sum(0) / total
    with np.errstate(divide="ignore", invalid="ignore"):
        s_x = -float(np.nansum(p_x * np.log(p_x)))
    if s_x == 0:
        return jnp.asarray(0.0, dtype=jnp.float32)
    return jnp.asarray((s_x - s_xy) / s_x, dtype=jnp.float32)


def theils_u(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Theil's U: how much knowing ``target`` reduces uncertainty in ``preds``.

    Asymmetric: ``U(preds|target) != U(target|preds)`` (reference ``theils_u.py:106-147``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 0, 1])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 0, 0])
        >>> from torchmetrics_tpu.functional.nominal.theils_u import theils_u
        >>> print(round(float(theils_u(preds, target)), 4))
        0.4427
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_dense_update(
        preds, target, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )
    return _theils_u_compute(confmat)


def theils_u_matrix(
    matrix: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Full (asymmetric) pairwise Theil's U matrix over dataset columns (reference ``theils_u.py:150-190``).

    One confusion matrix per unordered column pair: ``U(j|i)`` is computed from the
    transposed ``(i, j)`` table, halving the device scatters vs. iterating permutations.
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), dtype=np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        confmat = _nominal_dense_update(
            matrix[:, i], matrix[:, j], nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
        )
        out[i, j] = float(_theils_u_compute(confmat))
        out[j, i] = float(_theils_u_compute(confmat.T))
    return jnp.asarray(out)
