"""Pearson's contingency coefficient (reference ``functional/nominal/pearson.py``)."""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from torchmetrics_tpu.functional.nominal.utils import (
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _nominal_bins_update,
    _nominal_dense_update,
    _nominal_input_validation,
    _pairwise_matrix,
)

Array = jax.Array


def _pearsons_contingency_coefficient_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Fold a batch into the confusion matrix (reference ``pearson.py:30-52``)."""
    return _nominal_bins_update(
        preds, target, num_classes, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )


def _pearsons_contingency_coefficient_compute(confmat: Array) -> Array:
    """sqrt(phi^2 / (1 + phi^2)) (reference ``pearson.py:55-70``)."""
    cm = _drop_empty_rows_and_cols(np.asarray(confmat, dtype=np.float64))
    cm_sum = cm.sum()
    chi_squared = _compute_chi_squared(cm, bias_correction=False)
    phi_squared = chi_squared / cm_sum
    value = np.sqrt(phi_squared / (1 + phi_squared))
    return jnp.asarray(np.clip(value, 0.0, 1.0), dtype=jnp.float32)


def pearsons_contingency_coefficient(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Pearson's contingency coefficient between two categorical series (reference ``pearson.py:73-127``).

    Category values may be arbitrary; they are densified before binning.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 0, 1])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 0, 0])
        >>> from torchmetrics_tpu.functional.nominal.pearson import pearsons_contingency_coefficient
        >>> print(round(float(pearsons_contingency_coefficient(preds, target)), 4))
        0.6631
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_dense_update(
        preds, target, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )
    return _pearsons_contingency_coefficient_compute(confmat)


def pearsons_contingency_coefficient_matrix(
    matrix: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Pairwise contingency coefficients over dataset columns (reference ``pearson.py:130-169``)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)

    def _stat(x: Array, y: Array) -> Array:
        confmat = _nominal_dense_update(x, y, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update)
        return _pearsons_contingency_coefficient_compute(confmat)

    return _pairwise_matrix(matrix, _stat)
