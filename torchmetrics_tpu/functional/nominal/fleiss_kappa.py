"""Fleiss' kappa (reference ``functional/nominal/fleiss_kappa.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _fleiss_kappa_update(ratings: Array, mode: str = "counts") -> Array:
    """Normalize ratings to a per-sample category-count matrix (reference ``fleiss_kappa.py:20-46``)."""
    ratings = jnp.asarray(ratings)
    if mode == "probs":
        if ratings.ndim != 3 or not jnp.issubdtype(ratings.dtype, jnp.floating):
            raise ValueError(
                "If argument ``mode`` is 'probs', ratings must have 3 dimensions with the format"
                " [n_samples, n_categories, n_raters] and be floating point."
            )
        n_categories = ratings.shape[1]
        picked = ratings.argmax(axis=1)  # (n_samples, n_raters)
        one_hot = jax.nn.one_hot(picked, n_categories, axis=-1)  # (n_samples, n_raters, n_categories)
        return one_hot.sum(axis=1).astype(jnp.int32)
    if mode == "counts" and (ratings.ndim != 2 or jnp.issubdtype(ratings.dtype, jnp.floating)):
        raise ValueError(
            "If argument ``mode`` is `counts`, ratings must have 2 dimensions with the format"
            " [n_samples, n_categories] and be none floating point."
        )
    return ratings


def _fleiss_kappa_compute(counts: Array) -> Array:
    """kappa = (p_bar - pe_bar) / (1 - pe_bar) over the counts matrix (reference ``fleiss_kappa.py:49-66``)."""
    counts = jnp.asarray(counts, dtype=jnp.float32)
    total = counts.shape[0]
    n_rater = counts.sum(axis=1)
    num_raters = n_rater.max()

    p_i = counts.sum(axis=0) / (total * num_raters)
    p_j = ((counts**2).sum(axis=1) - num_raters) / (num_raters * (num_raters - 1))
    p_bar = p_j.mean()
    pe_bar = (p_i**2).sum()
    return (p_bar - pe_bar) / (1 - pe_bar + 1e-5)


def fleiss_kappa(ratings: Array, mode: str = "counts") -> Array:
    r"""Fleiss' kappa inter-rater agreement (reference ``fleiss_kappa.py:69-110``)."""
    if mode not in ("counts", "probs"):
        raise ValueError("Argument ``mode`` must be one of ['counts', 'probs']")
    counts = _fleiss_kappa_update(ratings, mode)
    return _fleiss_kappa_compute(counts)
