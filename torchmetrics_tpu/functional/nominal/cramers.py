"""Cramer's V (reference ``functional/nominal/cramers.py``)."""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from torchmetrics_tpu.functional.nominal.utils import (
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _nominal_bins_update,
    _nominal_dense_update,
    _nominal_input_validation,
    _pairwise_matrix,
    _unable_to_use_bias_correction_warning,
)

Array = jax.Array


def _cramers_v_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Fold a batch into the confusion matrix (reference ``cramers.py:33-55``)."""
    return _nominal_bins_update(
        preds, target, num_classes, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )


def _cramers_v_compute(confmat: Array, bias_correction: bool) -> Array:
    """V = sqrt(phi^2 / min(r-1, c-1)), optionally bias-corrected (reference ``cramers.py:58-88``)."""
    cm = _drop_empty_rows_and_cols(np.asarray(confmat, dtype=np.float64))
    cm_sum = cm.sum()
    chi_squared = _compute_chi_squared(cm, bias_correction)
    phi_squared = chi_squared / cm_sum
    n_rows, n_cols = cm.shape

    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, n_rows, n_cols, cm_sum
        )
        if min(rows_corrected, cols_corrected) == 1:
            _unable_to_use_bias_correction_warning(metric_name="Cramer's V")
            return jnp.asarray(float("nan"))
        value = np.sqrt(phi_squared_corrected / min(rows_corrected - 1, cols_corrected - 1))
    else:
        value = np.sqrt(phi_squared / min(n_rows - 1, n_cols - 1))
    return jnp.asarray(np.clip(value, 0.0, 1.0), dtype=jnp.float32)


def cramers_v(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Cramer's V association between two categorical series (reference ``cramers.py:91-141``).

    Category values may be arbitrary (floats, non-contiguous ints): they are densified
    before binning, unlike the reference which requires 0..k-1 codes.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 0, 1])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 0, 0])
        >>> from torchmetrics_tpu.functional.nominal.cramers import cramers_v
        >>> print(round(float(cramers_v(preds, target)), 4))
        0.4677
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_dense_update(
        preds, target, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )
    return _cramers_v_compute(confmat, bias_correction)


def cramers_v_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Pairwise Cramer's V over dataset columns (reference ``cramers.py:144-183``)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)

    def _stat(x: Array, y: Array) -> Array:
        confmat = _nominal_dense_update(x, y, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update)
        return _cramers_v_compute(confmat, bias_correction)

    return _pairwise_matrix(matrix, _stat)
