"""Shared machinery for the nominal-association statistics (reference ``functional/nominal/utils.py``).

Confusion-matrix accumulation runs on device; the association statistics themselves are
epoch-end scalars over a (classes x classes) table whose empty rows/columns must be
dropped (data-dependent shape), so the compute stage runs on host numpy — one tiny
matrix, fetched once.
"""

from __future__ import annotations

import itertools
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[Union[int, float]]) -> None:
    if nan_strategy not in ["replace", "drop"]:
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (int, float)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _handle_nan_in_data(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Tuple[Array, Array]:
    """Replace or drop NaN rows (reference ``utils.py:114-144``)."""
    if nan_strategy == "replace":
        return jnp.nan_to_num(preds, nan=nan_replace_value), jnp.nan_to_num(target, nan=nan_replace_value)
    keep = ~(jnp.isnan(preds) | jnp.isnan(target))
    return preds[keep], target[keep]


def _drop_empty_rows_and_cols(confmat: np.ndarray) -> np.ndarray:
    """Drop all-zero rows and columns (reference ``utils.py:60-79``)."""
    confmat = confmat[confmat.sum(1) != 0]
    return confmat[:, confmat.sum(0) != 0]


def _compute_expected_freqs(confmat: np.ndarray) -> np.ndarray:
    """Outer product of the margins over the total (reference ``utils.py:34-38``)."""
    margin_rows, margin_cols = confmat.sum(1), confmat.sum(0)
    return np.outer(margin_rows, margin_cols) / confmat.sum()


def _compute_chi_squared(confmat: np.ndarray, bias_correction: bool) -> float:
    """Chi-square test of independence, scipy-style Yates correction at df=1 (reference ``utils.py:41-57``)."""
    expected_freqs = _compute_expected_freqs(confmat)
    df = expected_freqs.size - sum(expected_freqs.shape) + expected_freqs.ndim - 1
    if df == 0:
        return 0.0
    if df == 1 and bias_correction:
        diff = expected_freqs - confmat
        direction = np.sign(diff)
        confmat = confmat + direction * np.minimum(0.5, np.abs(diff))
    return float(np.sum((confmat - expected_freqs) ** 2 / expected_freqs))


def _compute_phi_squared_corrected(phi_squared: float, n_rows: int, n_cols: int, cm_sum: float) -> float:
    """Bias-corrected phi squared (reference ``utils.py:82-92``)."""
    return max(0.0, phi_squared - ((n_rows - 1) * (n_cols - 1)) / (cm_sum - 1))


def _compute_rows_and_cols_corrected(n_rows: int, n_cols: int, cm_sum: float) -> Tuple[float, float]:
    """Bias-corrected row/column counts (reference ``utils.py:95-99``)."""
    rows_corrected = n_rows - (n_rows - 1) ** 2 / (cm_sum - 1)
    cols_corrected = n_cols - (n_cols - 1) ** 2 / (cm_sum - 1)
    return rows_corrected, cols_corrected


def _compute_bias_corrected_values(
    phi_squared: float, n_rows: int, n_cols: int, cm_sum: float
) -> Tuple[float, float, float]:
    """Bias-corrected phi squared and effective table shape (reference ``utils.py:102-108``)."""
    phi_squared_corrected = _compute_phi_squared_corrected(phi_squared, n_rows, n_cols, cm_sum)
    rows_corrected, cols_corrected = _compute_rows_and_cols_corrected(n_rows, n_cols, cm_sum)
    return phi_squared_corrected, rows_corrected, cols_corrected


def _unable_to_use_bias_correction_warning(metric_name: str) -> None:
    rank_zero_warn(
        f"Unable to compute {metric_name} using bias correction. Please consider to set `bias_correction=False`."
    )


def _nominal_bins_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str,
    nan_replace_value: Optional[Union[int, float]],
    confmat_update: Callable[[Array, Array, int], Array],
) -> Array:
    """Shared modular update: squeeze logits to labels, scrub NaNs, fold the table.

    Labels must already be dense 0..num_classes-1 codes (reference parity) — values
    outside that range are silently dropped by the scatter.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    return confmat_update(preds.astype(jnp.int32), target.astype(jnp.int32), num_classes)


def _nominal_dense_update(
    preds: Array,
    target: Array,
    nan_strategy: str,
    nan_replace_value: Optional[Union[int, float]],
    confmat_update: Callable[[Array, Array, int], Array],
) -> Array:
    """Single-shot functional update: relabel arbitrary category values to dense codes.

    The convenience functionals accept any category coding (floats, non-contiguous
    ints); binning raw values against ``len(unique)`` bins would silently drop
    out-of-range pairs, so NaNs are scrubbed first and the joint value set is
    densified via searchsorted before the device scatter.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)

    p = np.asarray(preds).reshape(-1)
    t = np.asarray(target).reshape(-1)
    uniq = np.unique(np.concatenate([p, t]))
    p_codes = np.searchsorted(uniq, p)
    t_codes = np.searchsorted(uniq, t)
    return confmat_update(jnp.asarray(p_codes, dtype=jnp.int32), jnp.asarray(t_codes, dtype=jnp.int32), len(uniq))


def _pairwise_matrix(
    matrix: Array,
    statistic: Callable[[Array, Array], Array],
) -> Array:
    """Symmetric pairwise association matrix over dataset columns (reference ``cramers.py:144-183``)."""
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), dtype=np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        out[i, j] = out[j, i] = float(statistic(matrix[:, i], matrix[:, j]))
    return jnp.asarray(out)
