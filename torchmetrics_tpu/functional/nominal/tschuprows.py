"""Tschuprow's T (reference ``functional/nominal/tschuprows.py``)."""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from torchmetrics_tpu.functional.nominal.utils import (
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _nominal_bins_update,
    _nominal_dense_update,
    _nominal_input_validation,
    _pairwise_matrix,
    _unable_to_use_bias_correction_warning,
)

Array = jax.Array


def _tschuprows_t_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Fold a batch into the confusion matrix (reference ``tschuprows.py:32-54``)."""
    return _nominal_bins_update(
        preds, target, num_classes, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )


def _tschuprows_t_compute(confmat: Array, bias_correction: bool) -> Array:
    """T = sqrt(phi^2 / sqrt((r-1)(c-1))), optionally bias-corrected (reference ``tschuprows.py:57-85``)."""
    cm = _drop_empty_rows_and_cols(np.asarray(confmat, dtype=np.float64))
    cm_sum = cm.sum()
    chi_squared = _compute_chi_squared(cm, bias_correction)
    phi_squared = chi_squared / cm_sum
    n_rows, n_cols = cm.shape

    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, n_rows, n_cols, cm_sum
        )
        if min(rows_corrected, cols_corrected) == 1:
            _unable_to_use_bias_correction_warning(metric_name="Tschuprow's T")
            return jnp.asarray(float("nan"))
        value = np.sqrt(phi_squared_corrected / np.sqrt((rows_corrected - 1) * (cols_corrected - 1)))
    else:
        value = np.sqrt(phi_squared / np.sqrt((n_rows - 1) * (n_cols - 1)))
    return jnp.asarray(np.clip(value, 0.0, 1.0), dtype=jnp.float32)


def tschuprows_t(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Tschuprow's T association between two categorical series (reference ``tschuprows.py:88-143``).

    Category values may be arbitrary; they are densified before binning.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([0, 1, 2, 2, 1, 0, 1, 2, 0, 1])
        >>> target = jnp.asarray([0, 1, 2, 1, 1, 0, 2, 2, 0, 0])
        >>> from torchmetrics_tpu.functional.nominal.tschuprows import tschuprows_t
        >>> print(round(float(tschuprows_t(preds, target)), 4))
        0.4677
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    confmat = _nominal_dense_update(
        preds, target, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update
    )
    return _tschuprows_t_compute(confmat, bias_correction)


def tschuprows_t_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    r"""Pairwise Tschuprow's T over dataset columns (reference ``tschuprows.py:146-186``)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)

    def _stat(x: Array, y: Array) -> Array:
        confmat = _nominal_dense_update(x, y, nan_strategy, nan_replace_value, _multiclass_confusion_matrix_update)
        return _tschuprows_t_compute(confmat, bias_correction)

    return _pairwise_matrix(matrix, _stat)
