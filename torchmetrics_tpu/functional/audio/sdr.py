"""Signal-to-distortion ratios (reference ``functional/audio/sdr.py``).

The SDR distortion filter is solved fully on device: FFT auto/cross-correlations, a
gather-built symmetric Toeplitz system, and ``jnp.linalg.solve`` — where the reference
reaches for the ``fast_bss_eval`` CPU conjugate-gradient extension (``sdr.py:30-34``).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix from the first row: one |i-j| gather (reference ``sdr.py:37-62``)."""
    length = vector.shape[-1]
    i = jnp.arange(length)
    return vector[..., jnp.abs(i[:, None] - i[None, :])]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int) -> Tuple[Array, Array]:
    """FFT-based autocorrelation of ``target`` and cross-correlation with ``preds`` (reference ``sdr.py:65-92``)."""
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR in dB via the optimal length-L distortion filter (reference ``sdr.py:95-190``).

    ``use_cg_iter`` is accepted for API parity; the dense on-device solve handles the
    512-tap system in one batched ``jnp.linalg.solve``.
    """
    _check_same_shape(preds, target)

    preds_dtype = preds.dtype
    preds = preds.astype(jnp.float64) if jax.config.jax_enable_x64 else preds.astype(jnp.float32)
    target = target.astype(preds.dtype)

    if zero_mean:
        preds = preds - preds.mean(axis=-1, keepdims=True)
        target = target - target.mean(axis=-1, keepdims=True)

    target = target / jnp.clip(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-6)
    preds = preds / jnp.clip(jnp.linalg.norm(preds, axis=-1, keepdims=True), 1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)
    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    r = _symmetric_toeplitz(r_0)
    sol = jnp.linalg.solve(r, b[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", b, sol)
    ratio = coh / (1 - coh)
    val = 10.0 * jnp.log10(ratio)
    return val.astype(preds_dtype) if preds_dtype in (jnp.float64,) else val.astype(jnp.float32)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR in dB (reference ``sdr.py:193-244``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(1)
        >>> target = jax.random.normal(key, (2, 100))
        >>> preds = target + 0.1 * jax.random.normal(jax.random.fold_in(key, 1), (2, 100))
        >>> from torchmetrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
        >>> print([round(float(x), 4) for x in scale_invariant_signal_distortion_ratio(preds, target)])
        [21.438, 20.9752]
    """
    _check_same_shape(preds, target)
    eps = float(jnp.finfo(jnp.asarray(preds).dtype).eps)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)
