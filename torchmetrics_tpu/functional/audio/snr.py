"""Signal-to-noise ratios (reference ``functional/audio/snr.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio
from torchmetrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR in dB over the trailing time axis (reference ``snr.py:22-63``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(1)
        >>> target = jax.random.normal(key, (2, 100))
        >>> preds = target + 0.1 * jax.random.normal(jax.random.fold_in(key, 1), (2, 100))
        >>> from torchmetrics_tpu.functional.audio.snr import signal_noise_ratio
        >>> print([round(float(x), 4) for x in signal_noise_ratio(preds, target)])
        [21.4689, 20.9864]
    """
    _check_same_shape(preds, target)
    eps = float(jnp.finfo(jnp.asarray(preds).dtype).eps)
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR: SI-SDR with zero-mean inputs (reference ``snr.py:66-93``)."""
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)


def complex_scale_invariant_signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """C-SI-SNR over complex (or trailing-dim-2 real) spectra (reference ``snr.py:96-140``)."""
    if jnp.iscomplexobj(preds):
        preds = jnp.stack([preds.real, preds.imag], axis=-1)
    if jnp.iscomplexobj(target):
        target = jnp.stack([target.real, target.imag], axis=-1)
    if preds.ndim < 3 or preds.shape[-1] != 2 or target.ndim < 3 or target.shape[-1] != 2:
        raise RuntimeError(
            "Predictions and targets are expected to have the shape (..., frequency, time, 2),"
            " but got {} and {}.".format(preds.shape, target.shape)
        )
    # treat the (frequency, time, re/im) block as one real-valued signal per sample
    preds = preds.reshape(*preds.shape[:-3], -1)
    target = target.reshape(*target.shape[:-3], -1)
    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=zero_mean)
