"""PESQ wrapper (reference ``functional/audio/pesq.py``).

Like the reference, this delegates to the external ``pesq`` C extension on host — the
ITU-T P.862 pipeline is a fixed DSP spec, not accelerator math. Gated on availability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.imports import _PESQ_AVAILABLE

Array = jax.Array

__doctest_requires__ = {("perceptual_evaluation_speech_quality",): ["pesq"]}


def perceptual_evaluation_speech_quality(
    preds: Array,
    target: Array,
    fs: int,
    mode: str,
    keep_same_device: bool = False,
    n_processes: int = 1,
) -> Array:
    """PESQ score per sample via the ``pesq`` package (reference ``pesq.py:24-91``)."""
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed. Either install as `pip install torchmetrics[audio]`"
            " or `pip install pesq`."
        )
    import pesq as pesq_backend

    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    _check_same_shape(preds, target)

    if preds.ndim == 1:
        pesq_val_np = pesq_backend.pesq(fs, np.asarray(target), np.asarray(preds), mode)
        pesq_val = jnp.asarray(pesq_val_np)
    else:
        preds_np = np.asarray(preds).reshape(-1, preds.shape[-1])
        target_np = np.asarray(target).reshape(-1, preds.shape[-1])
        if n_processes != 1:
            pesq_val_np = pesq_backend.pesq_batch(fs, target_np, preds_np, mode, n_processor=n_processes)
            pesq_val_np = np.array(pesq_val_np)
        else:
            pesq_val_np = np.empty(shape=(preds_np.shape[0]))
            for b in range(preds_np.shape[0]):
                pesq_val_np[b] = pesq_backend.pesq(fs, target_np[b, :], preds_np[b, :], mode)
        pesq_val = jnp.asarray(pesq_val_np).reshape(preds.shape[:-1])

    return pesq_val
