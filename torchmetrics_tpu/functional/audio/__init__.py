"""Functional audio metrics (reference ``src/torchmetrics/functional/audio/__init__.py``)."""

from torchmetrics_tpu.functional.audio.pit import permutation_invariant_training, pit_permutate
from torchmetrics_tpu.functional.audio.sdr import (
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
)
from torchmetrics_tpu.functional.audio.snr import (
    complex_scale_invariant_signal_noise_ratio,
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from torchmetrics_tpu.utilities.imports import _PESQ_AVAILABLE, _PYSTOI_AVAILABLE

__all__ = [
    "permutation_invariant_training",
    "pit_permutate",
    "scale_invariant_signal_distortion_ratio",
    "signal_distortion_ratio",
    "complex_scale_invariant_signal_noise_ratio",
    "scale_invariant_signal_noise_ratio",
    "signal_noise_ratio",
]

if _PESQ_AVAILABLE:
    from torchmetrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality  # noqa: F401

    __all__.append("perceptual_evaluation_speech_quality")

if _PYSTOI_AVAILABLE:
    from torchmetrics_tpu.functional.audio.stoi import short_time_objective_intelligibility  # noqa: F401

    __all__.append("short_time_objective_intelligibility")
