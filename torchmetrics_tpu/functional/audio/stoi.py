"""STOI wrapper (reference ``functional/audio/stoi.py``).

Delegates to the external ``pystoi`` package on host, like the reference. Gated on
availability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array

__doctest_requires__ = {("short_time_objective_intelligibility",): ["pystoi"]}


def short_time_objective_intelligibility(
    preds: Array, target: Array, fs: int, extended: bool = False, keep_same_device: bool = False
) -> Array:
    """STOI score per sample via ``pystoi`` (reference ``stoi.py:22-86``)."""
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "STOI metric requires that pystoi is installed. Either install as `pip install torchmetrics[audio]`"
            " or `pip install pystoi`."
        )
    from pystoi import stoi as stoi_backend

    _check_same_shape(preds, target)

    if preds.ndim == 1:
        stoi_val_np = stoi_backend(np.asarray(target), np.asarray(preds), fs, extended)
        return jnp.asarray(stoi_val_np)
    preds_np = np.asarray(preds).reshape(-1, preds.shape[-1])
    target_np = np.asarray(target).reshape(-1, preds.shape[-1])
    stoi_val_np = np.empty(shape=(preds_np.shape[0]))
    for b in range(preds_np.shape[0]):
        stoi_val_np[b] = stoi_backend(target_np[b, :], preds_np[b, :], fs, extended)
    return jnp.asarray(stoi_val_np).reshape(preds.shape[:-1])
