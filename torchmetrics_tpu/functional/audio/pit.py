"""Permutation-invariant training (reference ``functional/audio/pit.py``).

TPU-first matrix construction: the reference fills the speaker-pair metric matrix with
an S x S Python loop of separate metric calls (``pit.py:177-190``); here every pair is
evaluated in ONE batched call over a ``(batch * S * S)`` flattened layout, then the best
assignment is found by an on-device exhaustive search over the S! permutations (S is
small in speech separation) — or scipy's Hungarian solver on host for larger S, like the
reference (``pit.py:43-59``).
"""

from __future__ import annotations

from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_EXHAUSTIVE_SPK_LIMIT = 3  # S! permutations on device up to here; Hungarian beyond


def _gen_permutations(spk_num: int) -> Array:
    """All permutations of ``range(spk_num)``, shape ``(perm_num, spk_num)``."""
    return jnp.asarray(list(permutations(range(spk_num))))


def _find_best_perm_by_exhaustive_method(metric_mtx: Array, maximize: bool) -> Tuple[Array, Array]:
    """Score all S! assignments with one gather and reduce (reference ``pit.py:62-103``)."""
    spk_num = metric_mtx.shape[-1]
    perms = _gen_permutations(spk_num)  # (P, S): prediction index for each target slot
    # metric_of_ps[b, p] = mean_s metric_mtx[b, s, perms[p, s]]
    gathered = metric_mtx[:, jnp.arange(spk_num)[None, :], perms]  # (B, P, S)
    metric_of_ps = gathered.mean(axis=-1)  # (B, P)
    best_indexes = jnp.argmax(metric_of_ps, axis=-1) if maximize else jnp.argmin(metric_of_ps, axis=-1)
    best_metric = jnp.take_along_axis(metric_of_ps, best_indexes[:, None], axis=-1)[:, 0]
    best_perm = perms[best_indexes]
    return best_metric, best_perm


def _find_best_perm_by_linear_sum_assignment(metric_mtx: Array, maximize: bool) -> Tuple[Array, Array]:
    """Hungarian solve on host for larger speaker counts (reference ``pit.py:43-59``)."""
    from scipy.optimize import linear_sum_assignment

    mtx = np.asarray(metric_mtx)
    best_perm = jnp.asarray(np.array([linear_sum_assignment(m, maximize)[1] for m in mtx]))
    best_metric = jnp.take_along_axis(metric_mtx, best_perm[:, :, None], axis=2).mean(axis=(-1, -2))
    return best_metric, best_perm


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    mode: str = "speaker-wise",
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """Best metric value and speaker assignment per sample (reference ``pit.py:106-213``).

    ``preds``/``target`` are ``(batch, spk, ...)``; ``metric_func`` maps batched
    ``(preds, target)`` pairs to ``(batch,)`` values.
    """
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if mode not in ["speaker-wise", "permutation-wise"]:
        raise ValueError(f'mode can only be "speaker-wise" or "permutation-wise" but got {mode}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    maximize = eval_func == "max"
    batch_size, spk_num = target.shape[0:2]

    if mode == "permutation-wise":
        # evaluate the metric on whole permutations (joint metrics), one batched call
        perms = _gen_permutations(spk_num)  # (P, S)
        perm_num = perms.shape[0]
        ppreds = preds[:, perms, ...].reshape(batch_size * perm_num, *preds.shape[1:])
        ptarget = jnp.repeat(target, perm_num, axis=0)
        metric_of_ps = metric_func(ppreds, ptarget, **kwargs)
        metric_of_ps = jnp.mean(metric_of_ps.reshape(batch_size, perm_num, -1), axis=-1)
        best_indexes = jnp.argmax(metric_of_ps, axis=-1) if maximize else jnp.argmin(metric_of_ps, axis=-1)
        best_metric = jnp.take_along_axis(metric_of_ps, best_indexes[:, None], axis=-1)[:, 0]
        return best_metric, perms[best_indexes]

    # speaker-wise: all S*S pairs in one metric call
    rest = preds.shape[2:]
    preds_pairs = jnp.broadcast_to(preds[:, None, :, ...], (batch_size, spk_num, spk_num, *rest))
    target_pairs = jnp.broadcast_to(target[:, :, None, ...], (batch_size, spk_num, spk_num, *rest))
    flat_metric = metric_func(
        preds_pairs.reshape(batch_size * spk_num * spk_num, *rest),
        target_pairs.reshape(batch_size * spk_num * spk_num, *rest),
        **kwargs,
    )
    metric_mtx = flat_metric.reshape(batch_size, spk_num, spk_num)  # [b, target, pred]

    if spk_num <= _EXHAUSTIVE_SPK_LIMIT:
        return _find_best_perm_by_exhaustive_method(metric_mtx, maximize)
    return _find_best_perm_by_linear_sum_assignment(metric_mtx, maximize)


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` speakers by the best permutation (reference ``pit.py:216-229``)."""
    return jnp.take_along_axis(preds, perm[(...,) + (None,) * (preds.ndim - 2)], axis=1)
