"""Universal Image Quality Index (reference ``functional/image/uqi.py``).

Same stacked depthwise-conv trick as SSIM: one conv produces all five moment maps.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.helper import (
    _check_image_shape,
    _filter_separable_2d,
    _gaussian_np,
    _reflect_pad_2d,
)
from torchmetrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _uqi_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate BxCxHxW inputs (reference ``uqi.py:25-47``)."""
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    return _check_image_shape(preds, target)


def _uqi_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """UQI over gaussian-windowed moments (reference ``uqi.py:50-119``)."""
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2

    preds = _reflect_pad_2d(preds, pad_h, pad_w)
    target = _reflect_pad_2d(target, pad_h, pad_w)

    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])
    outputs = _filter_separable_2d(
        input_list, _gaussian_np(kernel_size[0], sigma[0]), _gaussian_np(kernel_size[1], sigma[1])
    )
    b = preds.shape[0]
    mu_pred, mu_target, e_pp, e_tt, e_pt = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pp - mu_pred_sq
    sigma_target_sq = e_tt - mu_target_sq
    sigma_pred_target = e_pt - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq + jnp.finfo(sigma_pred_sq.dtype).eps

    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower)
    uqi_idx = uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w]
    return reduce(uqi_idx, reduction)


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """UQI (reference ``uqi.py:122-161``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.uqi import universal_image_quality_index
        >>> print(round(float(universal_image_quality_index(preds, target)), 4))
        0.9589
    """
    preds, target = _uqi_update(preds, target)
    return _uqi_compute(preds, target, kernel_size, sigma, reduction)
