"""Image gradients by finite difference (reference ``functional/image/gradients.py``)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _image_gradients_validate(img: Array) -> None:
    """Validate 4D input (reference ``gradients.py:21-27``)."""
    if not isinstance(img, (jax.Array, jnp.ndarray)):
        raise TypeError(f"The `img` expects a value of <Array> type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    """(dy, dx), last row/col zero-padded (reference ``gradients.py:30-48``)."""
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """Finite-difference image gradients (reference ``gradients.py:51-88``)."""
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
