"""Spectral Distortion Index D_lambda (reference ``functional/image/d_lambda.py``).

TPU-first: all C·(C−1)/2 channel pairs are scored in ONE batched UQI call — the pair
(k, r) images are stacked along the batch axis and a single stacked depthwise conv
evaluates every pair, instead of the reference's per-k Python loop of separate UQI
calls (``d_lambda.py:54-76``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.helper import _check_image_shape
from torchmetrics_tpu.functional.image.uqi import universal_image_quality_index
from torchmetrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _spectral_distortion_index_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate BxCxHxW inputs (reference ``d_lambda.py:24-46``)."""
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    return _check_image_shape(preds, target)


def _pairwise_uqi_matrix(x: Array) -> Array:
    """(C,C) matrix of mean UQI between every channel pair of the batch ``x``.

    Upper-triangle pairs are scored in one batched call over (P·B, 1, H, W) stacks.
    """
    b, c, h, w = x.shape
    pairs = [(k, r) for k in range(c) for r in range(k + 1, c)]
    if not pairs:
        return jnp.zeros((c, c), dtype=x.dtype)
    stack1 = jnp.concatenate([x[:, k : k + 1] for k, _ in pairs])  # (P*B, 1, H, W)
    stack2 = jnp.concatenate([x[:, r : r + 1] for _, r in pairs])
    scores = universal_image_quality_index(stack1, stack2, reduction="none")  # (P*B, 1, H, W)
    scores = scores.reshape(len(pairs), b, -1).mean(axis=(1, 2))
    m = jnp.zeros((c, c), dtype=scores.dtype)
    rows = jnp.asarray([k for k, _ in pairs])
    cols = jnp.asarray([r for _, r in pairs])
    m = m.at[rows, cols].set(scores)
    return m + m.T


def _spectral_distortion_index_compute(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """D_lambda from the two pairwise-UQI matrices (reference ``d_lambda.py:49-100``)."""
    length = preds.shape[1]
    m1 = _pairwise_uqi_matrix(target)
    m2 = _pairwise_uqi_matrix(preds)

    diff = jnp.abs(m1 - m2) ** p
    if length == 1:
        output = diff ** (1.0 / p)
    else:
        output = (1.0 / (length * (length - 1)) * jnp.sum(diff)) ** (1.0 / p)
    return reduce(output, reduction)


def spectral_distortion_index(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """D_lambda (reference ``d_lambda.py:103-147``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.d_lambda import spectral_distortion_index
        >>> print(round(float(spectral_distortion_index(preds, target)), 4))
        0.0002
    """
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    preds, target = _spectral_distortion_index_update(preds, target)
    return _spectral_distortion_index_compute(preds, target, p, reduction)
