"""PSNR with blocked effect (reference ``functional/image/psnrb.py``).

TPU-first: the block/non-block column selections are precomputed boolean masks applied
as weighted reductions (static shapes) instead of the reference's host-side
``set().symmetric_difference`` index lists (``psnrb.py:30-36``).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _compute_bef(x: Array, block_size: int = 8) -> Array:
    """Blocking effect factor of a grayscale NCHW batch (reference ``psnrb.py:21-60``)."""
    _, channels, height, width = x.shape
    if channels > 1:
        raise ValueError(f"`psnrb` metric expects grayscale images, but got images with {channels} channels.")

    h_mask = np.zeros(width - 1, dtype=bool)
    h_mask[block_size - 1 : width - 1 : block_size] = True
    v_mask = np.zeros(height - 1, dtype=bool)
    v_mask[block_size - 1 : height - 1 : block_size] = True
    h_b = jnp.asarray(h_mask)
    v_b = jnp.asarray(v_mask)

    h_diff_sq = (x[:, :, :, :-1] - x[:, :, :, 1:]) ** 2  # (B,1,H,W-1)
    v_diff_sq = (x[:, :, :-1, :] - x[:, :, 1:, :]) ** 2  # (B,1,H-1,W)

    d_b = jnp.sum(h_diff_sq * h_b) + jnp.sum(v_diff_sq * v_b[None, None, :, None])
    d_bc = jnp.sum(h_diff_sq * ~h_b) + jnp.sum(v_diff_sq * (~v_b)[None, None, :, None])

    n_hb = height * (width / block_size) - 1
    n_hbc = (height * (width - 1)) - n_hb
    n_vb = width * (height / block_size) - 1
    n_vbc = (width * (height - 1)) - n_vb
    d_b = d_b / (n_hb + n_vb)
    d_bc = d_bc / (n_hbc + n_vbc)
    t = math.log2(block_size) / math.log2(min(height, width))
    return jnp.where(d_b > d_bc, t * (d_b - d_bc), 0.0)


def _psnrb_compute(sum_squared_error: Array, bef: Array, n_obs: Array, data_range: Array) -> Array:
    """PSNR-B from accumulated SSE + blocking effect (reference ``psnrb.py:63-79``)."""
    sum_squared_error = sum_squared_error / n_obs + bef
    return jnp.where(
        data_range > 2,
        10 * jnp.log10(data_range**2 / sum_squared_error),
        10 * jnp.log10(1.0 / sum_squared_error),
    )


def _psnrb_update(preds: Array, target: Array, block_size: int = 8) -> Tuple[Array, Array, Array]:
    """SSE, blocking effect, count (reference ``psnrb.py:82-94``)."""
    sum_squared_error = jnp.sum((preds - target) ** 2)
    n_obs = jnp.asarray(target.size)
    bef = _compute_bef(preds, block_size=block_size)
    return sum_squared_error, bef, n_obs


def peak_signal_noise_ratio_with_blocked_effect(preds: Array, target: Array, block_size: int = 8) -> Array:
    """PSNR-B (reference ``psnrb.py:97-131``)."""
    data_range = target.max() - target.min()
    sum_squared_error, bef, n_obs = _psnrb_update(preds, target, block_size=block_size)
    return _psnrb_compute(sum_squared_error, bef, n_obs, data_range)
