"""Peak signal-to-noise ratio (reference ``functional/image/psnr.py``)."""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _psnr_compute(
    sum_squared_error: Array,
    n_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """PSNR from accumulated SSE (reference ``psnr.py:23-55``)."""
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / n_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(jnp.asarray(base)))
    return reduce(psnr_vals, reduction=reduction)


def _psnr_update(
    preds: Array,
    target: Array,
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Tuple[Array, Array]:
    """SSE + count, optionally per reduced-dim slice (reference ``psnr.py:58-87``)."""
    if dim is None:
        diff = preds - target
        sum_squared_error = jnp.sum(diff * diff)
        n_obs = jnp.asarray(target.size)
        return sum_squared_error, n_obs

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    if not dim_list:
        n_obs = jnp.asarray(target.size)
    else:
        n_obs = jnp.prod(jnp.asarray([target.shape[d] for d in dim_list]))
        n_obs = jnp.broadcast_to(n_obs, sum_squared_error.shape)
    return sum_squared_error, n_obs


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """PSNR (reference ``psnr.py:90-142``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.psnr import peak_signal_noise_ratio
        >>> print(round(float(peak_signal_noise_ratio(preds, target)), 4))
        19.7839
    """
    if dim is None and reduction != "elementwise_mean":
        from torchmetrics_tpu.utilities.prints import rank_zero_warn

        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = target.max() - target.min()
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = jnp.asarray(data_range[1] - data_range[0])
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, n_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, n_obs, data_range, base=base, reduction=reduction)
