"""RASE — relative average spectral error (reference ``functional/image/rase.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.helper import _uniform_filter
from torchmetrics_tpu.functional.image.rmse_sw import _rmse_sw_compute, _rmse_sw_update

Array = jax.Array


def _rase_update(
    preds: Array,
    target: Array,
    window_size: int,
    rmse_map: Optional[Array],
    target_sum: Optional[Array],
    total_images: Optional[Array],
) -> Tuple[Array, Array, Array]:
    """Accumulate windowed RMSE map + windowed target mean (reference ``rase.py:22-49``)."""
    _, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images
    )
    this_target_sum = jnp.sum(_uniform_filter(target, window_size) / (window_size**2), axis=0)
    target_sum = (target_sum if target_sum is not None else 0.0) + this_target_sum
    return rmse_map, target_sum, total_images


def _rase_compute(rmse_map: Array, target_sum: Array, total_images: Array, window_size: int) -> Array:
    """RASE from accumulated maps (reference ``rase.py:52-72``)."""
    _, rmse_map = _rmse_sw_compute(rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images)
    target_mean = target_sum / total_images
    target_mean = target_mean.mean(0)  # mean over image channels
    rase_map = 100 / target_mean * jnp.sqrt(jnp.mean(rmse_map**2, axis=0))
    crop_slide = round(window_size / 2)
    return jnp.mean(rase_map[crop_slide:-crop_slide, crop_slide:-crop_slide])


def relative_average_spectral_error(preds: Array, target: Array, window_size: int = 8) -> Array:
    """RASE (reference ``rase.py:75-107``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.rase import relative_average_spectral_error
        >>> print(round(float(relative_average_spectral_error(preds, target)), 4))
        1024.0444
    """
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_map, target_sum, total_images = _rase_update(
        preds, target, window_size, rmse_map=None, target_sum=None, total_images=None
    )
    return _rase_compute(rmse_map, target_sum, total_images, window_size)
