"""SSIM / Multi-Scale SSIM (reference ``functional/image/ssim.py``).

TPU-first: the five moment maps (μ_p, μ_t, E[p²], E[t²], E[pt]) come from ONE
depthwise convolution over a stacked (5B, C, H, W) input — a single MXU-friendly conv
per scale, exactly the batching trick the reference uses (``ssim.py:148-152``), with
``lax.reduce_window`` average pooling between MS-SSIM scales.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

import numpy as np

from torchmetrics_tpu.functional.image.helper import (
    _avg_pool2d,
    _avg_pool3d,
    _filter_separable_2d,
    _filter_separable_3d,
    _gaussian_np,
    _reflect_pad_2d,
    _reflect_pad_3d,
)
from torchmetrics_tpu.utilities.checks import _check_same_shape
from torchmetrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _ssim_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate/coerce SSIM inputs (reference ``ssim.py:26-41``)."""
    if preds.dtype != target.dtype:
        target = target.astype(preds.dtype)
    _check_same_shape(preds, target)
    if preds.ndim not in (4, 5):
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


def _ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Per-image SSIM via one stacked depthwise conv (reference ``ssim.py:44-188``)."""
    is_3d = preds.ndim == 5

    if not isinstance(kernel_size, Sequence):
        kernel_size = 3 * [kernel_size] if is_3d else 2 * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = 3 * [sigma] if is_3d else 2 * [sigma]

    if len(kernel_size) != preds.ndim - 2 or len(kernel_size) not in (2, 3):
        raise ValueError(
            f"`kernel_size` has dimension {len(kernel_size)}, but expected to be two less that target dimensionality,"
            f" which is: {preds.ndim}"
        )
    if len(sigma) != preds.ndim - 2 or len(sigma) not in (2, 3):
        raise ValueError(
            f"`sigma` has dimension {len(sigma)}, but expected to be two less that target dimensionality,"
            f" which is: {preds.ndim}"
        )
    if return_full_image and return_contrast_sensitivity:
        raise ValueError("Arguments `return_full_image` and `return_contrast_sensitivity` are mutually exclusive.")
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    if data_range is None:
        data_range = jnp.maximum(preds.max() - preds.min(), target.max() - target.min())
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = data_range[1] - data_range[0]

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2

    channel = preds.shape[1]
    dtype = preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)
    gauss_kernel_size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]

    pad_h = (gauss_kernel_size[0] - 1) // 2
    pad_w = (gauss_kernel_size[1] - 1) // 2

    if is_3d:
        pad_d = (gauss_kernel_size[2] - 1) // 2
        preds = _reflect_pad_3d(preds, pad_h, pad_w, pad_d)
        target = _reflect_pad_3d(target, pad_h, pad_w, pad_d)
    else:
        preds = _reflect_pad_2d(preds, pad_h, pad_w)
        target = _reflect_pad_2d(target, pad_h, pad_w)

    # Both windows are separable (gaussian = outer product, uniform = (1/k)⊗(1/k)),
    # so the five moment maps come from band-matrix matmul passes on a 5B stack.
    if gaussian_kernel:
        k1d = [_gaussian_np(gauss_kernel_size[i], sigma[i]) for i in range(len(sigma))]
    else:
        k1d = [np.full(k, 1.0 / k) for k in kernel_size]

    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])  # (5B, C, ...)
    if is_3d:
        outputs = _filter_separable_3d(input_list, k1d[0], k1d[1], k1d[2])
    else:
        outputs = _filter_separable_2d(input_list, k1d[0], k1d[1])
    b = preds.shape[0]
    mu_pred, mu_target, e_pp, e_tt, e_pt = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pp - mu_pred_sq
    sigma_target_sq = e_tt - mu_target_sq
    sigma_pred_target = e_pt - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_idx_full_image = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)

    if is_3d:
        ssim_idx = ssim_idx_full_image[..., pad_h:-pad_h, pad_w:-pad_w, pad_d:-pad_d]
    else:
        ssim_idx = ssim_idx_full_image[..., pad_h:-pad_h, pad_w:-pad_w]

    if return_contrast_sensitivity:
        contrast_sensitivity = upper / lower
        if is_3d:
            contrast_sensitivity = contrast_sensitivity[..., pad_h:-pad_h, pad_w:-pad_w, pad_d:-pad_d]
        else:
            contrast_sensitivity = contrast_sensitivity[..., pad_h:-pad_h, pad_w:-pad_w]
        return ssim_idx.reshape(b, -1).mean(-1), contrast_sensitivity.reshape(b, -1).mean(-1)

    if return_full_image:
        return ssim_idx.reshape(b, -1).mean(-1), ssim_idx_full_image

    return ssim_idx.reshape(b, -1).mean(-1)


def _ssim_compute(similarities: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    """Reduce per-image similarities (reference ``ssim.py:191-210``)."""
    return reduce(similarities, reduction)


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """SSIM (reference ``ssim.py:213-287``).

    Example:
        >>> import jax.numpy as jnp
        >>> from torchmetrics_tpu.functional.image import structural_similarity_index_measure
        >>> img = jnp.ones((1, 3, 16, 16)) * 0.5
        >>> print(round(float(structural_similarity_index_measure(img, img, data_range=1.0)), 4))
        1.0
    """
    preds, target = _ssim_check_inputs(preds, target)
    similarity_pack = _ssim_update(
        preds,
        target,
        gaussian_kernel,
        sigma,
        kernel_size,
        data_range,
        k1,
        k2,
        return_full_image,
        return_contrast_sensitivity,
    )
    if isinstance(similarity_pack, tuple):
        similarity, image = similarity_pack
        return _ssim_compute(similarity, reduction), image
    return _ssim_compute(similarity_pack, reduction)


def _get_normalized_sim_and_cs(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    normalize: Optional[str] = None,
) -> Tuple[Array, Array]:
    sim, contrast_sensitivity = _ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, return_contrast_sensitivity=True
    )
    if normalize == "relu":
        sim = jax.nn.relu(sim)
        contrast_sensitivity = jax.nn.relu(contrast_sensitivity)
    return sim, contrast_sensitivity


def _multiscale_ssim_update(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Per-image MS-SSIM over len(betas) scales (reference ``ssim.py:317-419``)."""
    mcs_list: List[Array] = []

    is_3d = preds.ndim == 5
    if not isinstance(kernel_size, Sequence):
        kernel_size = 3 * [kernel_size] if is_3d else 2 * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = 3 * [sigma] if is_3d else 2 * [sigma]

    if preds.shape[-1] < 2 ** len(betas) or preds.shape[-2] < 2 ** len(betas):
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** len(betas)}."
        )
    _betas_div = max(1, (len(betas) - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[0]},"
            f" the image height must be larger than {(kernel_size[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {len(betas)} and kernel size {kernel_size[1]},"
            f" the image width must be larger than {(kernel_size[1] - 1) * _betas_div}."
        )

    sim = None
    for _ in range(len(betas)):
        sim, contrast_sensitivity = _get_normalized_sim_and_cs(
            preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, normalize=normalize
        )
        mcs_list.append(contrast_sensitivity)
        if len(kernel_size) == 2:
            preds = _avg_pool2d(preds)
            target = _avg_pool2d(target)
        else:
            preds = _avg_pool3d(preds)
            target = _avg_pool3d(target)

    mcs_list[-1] = sim
    mcs_stack = jnp.stack(mcs_list)

    if normalize == "simple":
        mcs_stack = (mcs_stack + 1) / 2

    betas_arr = jnp.asarray(betas).reshape(-1, 1)
    mcs_weighted = mcs_stack**betas_arr
    return jnp.prod(mcs_weighted, axis=0)


def _multiscale_ssim_compute(similarities: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    """Reduce per-image MS-SSIM values."""
    return reduce(similarities, reduction)


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = "relu",
) -> Array:
    """MS-SSIM (reference ``ssim.py:422-496``)."""
    if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
    preds, target = _ssim_check_inputs(preds, target)
    similarities = _multiscale_ssim_update(
        preds, target, gaussian_kernel, sigma, kernel_size, data_range, k1, k2, betas, normalize
    )
    return _multiscale_ssim_compute(similarities, reduction)
