"""Spectral Angle Mapper (reference ``functional/image/sam.py``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.helper import _check_image_shape
from torchmetrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _sam_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate multispectral BxCxHxW inputs (reference ``sam.py:24-48``)."""
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_image_shape(preds, target)
    if preds.shape[1] <= 1:
        raise ValueError(
            "Expected channel dimension of `preds` and `target` to be larger than 1."
            f" Got preds: {preds.shape[1]} and target: {target.shape[1]}."
        )
    return preds, target


def _sam_compute(preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    """Per-pixel spectral angle (reference ``sam.py:51-77``)."""
    dot_product = (preds * target).sum(axis=1)
    preds_norm = jnp.linalg.norm(preds, axis=1)
    target_norm = jnp.linalg.norm(target, axis=1)
    sam_score = jnp.arccos(jnp.clip(dot_product / (preds_norm * target_norm), -1.0, 1.0))
    return reduce(sam_score, reduction)


def spectral_angle_mapper(preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    """SAM (reference ``sam.py:80-118``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.sam import spectral_angle_mapper
        >>> print(round(float(spectral_angle_mapper(preds, target)), 4))
        0.0869
    """
    preds, target = _sam_update(preds, target)
    return _sam_compute(preds, target, reduction)
