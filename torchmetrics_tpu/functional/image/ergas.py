"""ERGAS — relative global dimensionless synthesis error (reference ``functional/image/ergas.py``)."""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from torchmetrics_tpu.functional.image.helper import _check_image_shape
from torchmetrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _ergas_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate BxCxHxW inputs (reference ``ergas.py:24-46``)."""
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    return _check_image_shape(preds, target)


def _ergas_compute(
    preds: Array,
    target: Array,
    ratio: Union[int, float] = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Per-image ERGAS (reference ``ergas.py:49-92``)."""
    b, c, h, w = preds.shape
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = jnp.mean(target, axis=2)

    ergas_score = 100 * ratio * jnp.sqrt(jnp.sum((rmse_per_band / mean_target) ** 2, axis=1) / c)
    return reduce(ergas_score, reduction)


def error_relative_global_dimensionless_synthesis(
    preds: Array,
    target: Array,
    ratio: Union[int, float] = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """ERGAS (reference ``ergas.py:95-133``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.ergas import error_relative_global_dimensionless_synthesis
        >>> print(round(float(error_relative_global_dimensionless_synthesis(preds, target)), 4))
        63.5037
    """
    preds, target = _ergas_update(preds, target)
    return _ergas_compute(preds, target, ratio, reduction)
