"""LPIPS distance pipeline (reference ``functional/image/lpips.py``).

The reference vendors torchvision AlexNet/VGG/SqueezeNet backbones plus bundled linear
heads (``lpips_models/*.pth``). The TPU build ships the full distance *pipeline* (input
scaling, per-layer unit normalization, squared diff, 1×1 linear heads, spatial
averaging, layer sum), native Flax backbones (``models/{alexnet,vgg,squeezenet}.py``),
and the **learned LPIPS heads converted and bundled** (``_weights/lpips_heads.npz``,
from the reference's checkpoints loaded at ``lpips.py:286`` — see
``scripts/convert_lpips_heads.py``). Backbone ImageNet weights are NOT bundled
(zero-egress environment): string ``net_type`` builds a deterministic randomly
initialised backbone and warns — scores are then self-consistent but not canonical
LPIPS until a torchvision checkpoint is converted in via ``backbone_state_dict``.
"""

from __future__ import annotations

from functools import lru_cache
from pathlib import Path
from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array

_HEADS_FILE = Path(__file__).resolve().parent / "_weights" / "lpips_heads.npz"
_N_HEADS = {"alex": 5, "vgg": 5, "squeeze": 7}

# ImageNet-derived scaling constants (reference ``lpips.py:196-203``)
_SHIFT = jnp.asarray([-0.030, -0.088, -0.188])[None, :, None, None]
_SCALE = jnp.asarray([0.458, 0.448, 0.450])[None, :, None, None]


def normalize_tensor(in_feat: Array, eps: float = 1e-10) -> Array:
    """Unit-normalize along channels (reference ``lpips.py:187-190``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> key = jax.random.PRNGKey(42)
        >>> preds = jax.random.uniform(key, (2, 3, 16, 16))
        >>> target = preds * 0.75 + 0.1
        >>> from torchmetrics_tpu.functional.image.lpips import normalize_tensor
        >>> print(normalize_tensor(preds, target).shape)
        (2, 3, 16, 16)
    """
    norm_factor = jnp.sqrt(jnp.sum(in_feat**2, axis=1, keepdims=True))
    return in_feat / (norm_factor + eps)


def spatial_average(in_tens: Array, keepdim: bool = True) -> Array:
    """Mean over H, W (reference ``lpips.py:177-179``)."""
    return in_tens.mean(axis=(2, 3), keepdims=keepdim)


def upsample(in_tens: Array, out_hw: Tuple[int, int] = (64, 64)) -> Array:
    """Bilinear upsample to ``out_hw`` (reference ``lpips.py:182-184``)."""
    b, c = in_tens.shape[:2]
    return jax.image.resize(in_tens, (b, c, *out_hw), method="bilinear")


def scaling_layer(inp: Array) -> Array:
    """Shift/scale RGB input (reference ``lpips.py:193-203``)."""
    return (inp - _SHIFT) / _SCALE


def _lpips_distance(
    feats_fn: Callable[[Array], Sequence[Array]],
    img1: Array,
    img2: Array,
    lin_weights: Optional[Sequence[Array]] = None,
    normalize: bool = False,
    spatial: bool = False,
) -> Array:
    """Full LPIPS forward for a backbone (reference ``_LPIPS.forward``, ``lpips.py:291-320``)."""
    if normalize:  # [0,1] -> [-1,1]
        img1 = 2 * img1 - 1
        img2 = 2 * img2 - 1
    in0, in1 = scaling_layer(img1), scaling_layer(img2)
    outs0, outs1 = feats_fn(in0), feats_fn(in1)

    res: List[Array] = []
    for kk in range(len(outs0)):
        feats0 = normalize_tensor(outs0[kk])
        feats1 = normalize_tensor(outs1[kk])
        diff = (feats0 - feats1) ** 2
        if lin_weights is not None:
            w = lin_weights[kk].reshape(1, -1, 1, 1)
            lin_out = (diff * w).sum(axis=1, keepdims=True)
        else:
            lin_out = diff.sum(axis=1, keepdims=True)
        if spatial:
            res.append(upsample(lin_out, out_hw=img1.shape[2:]))
        else:
            res.append(spatial_average(lin_out, keepdim=True))
    val = res[0]
    for layer in res[1:]:
        val = val + layer
    return val


def make_lpips_net(
    feats_fn: Callable[[Array], Sequence[Array]],
    lin_weights: Optional[Sequence[Array]] = None,
    spatial: bool = False,
) -> Callable[..., Array]:
    """Compose a backbone + heads into the ``net(img1, img2, normalize=...)`` callable."""

    def net(img1: Array, img2: Array, normalize: bool = False) -> Array:
        return _lpips_distance(feats_fn, img1, img2, lin_weights, normalize, spatial)

    return net


def load_lpips_heads(net_type: str = "alex") -> List[Array]:
    """Load the bundled learned 1x1 head weights for a backbone, as flat ``(C,)`` arrays.

    Converted from the reference's ``lpips_models/{alex,squeeze,vgg}.pth`` (the LPIPS
    paper's learned heads, loaded by the reference at ``lpips.py:286``) by
    ``scripts/convert_lpips_heads.py``.
    """
    if net_type not in _N_HEADS:
        raise ValueError(f"Argument `net_type` must be one of {tuple(_N_HEADS)}, but got {net_type}.")
    import numpy as np

    with np.load(_HEADS_FILE) as data:
        return [jnp.asarray(data[f"{net_type}_lin{i}"]) for i in range(_N_HEADS[net_type])]


def lpips_network(
    net_type: str = "alex",
    backbone_state_dict: Optional[Mapping[str, Any]] = None,
    backbone_variables: Optional[Mapping[str, Any]] = None,
    spatial: bool = False,
    allow_random_backbone: bool = False,
) -> Callable[..., Array]:
    """Build the default ``net(img1, img2, normalize=...)`` for a string backbone.

    Uses the bundled learned heads plus the native Flax backbone. Without
    ``backbone_state_dict``/``backbone_variables`` this RAISES unless
    ``allow_random_backbone=True`` (a randomly-initialised backbone yields
    plausible-looking but non-canonical LPIPS; the reference hard-errors when the
    lpips package is absent). With the opt-in, the backbone is deterministically
    randomly initialised and a warning is emitted: distances are then valid for
    relative comparison within one configuration only.
    """
    if net_type not in _N_HEADS:
        raise ValueError(f"Argument `net_type` must be one of {tuple(_N_HEADS)}, but got {net_type}.")
    if backbone_state_dict is None and backbone_variables is None:
        if not allow_random_backbone:
            raise RuntimeError(
                f"No pretrained `{net_type}` backbone weights were supplied and none are bundled (the learned"
                " LPIPS heads are), so scores would come from a randomly-initialised backbone —"
                " plausible-looking but not canonical LPIPS. Pass `backbone_state_dict=` (a torchvision"
                " checkpoint) or `backbone_variables=` for exact values, or opt in explicitly with"
                " `allow_random_backbone=True`."
            )
        from torchmetrics_tpu.utilities.prints import rank_zero_warn

        rank_zero_warn(
            f"Using a deterministic randomly-initialised `{net_type}` backbone (`allow_random_backbone=True`):"
            " scores are self-consistent but not canonical LPIPS."
        )
        return _default_lpips_network(net_type, spatial)
    feats_fn = _lpips_backbone_builder(net_type)(
        state_dict=backbone_state_dict, variables=backbone_variables
    )
    return make_lpips_net(feats_fn, lin_weights=load_lpips_heads(net_type), spatial=spatial)


def _lpips_backbone_builder(net_type: str) -> Callable[..., Callable[[Array], Sequence[Array]]]:
    if net_type == "alex":
        from torchmetrics_tpu.models.alexnet import alexnet_lpips_extractor as build
    elif net_type == "vgg":
        from torchmetrics_tpu.models.vgg import vgg16_lpips_extractor as build
    else:
        from torchmetrics_tpu.models.squeezenet import squeezenet_lpips_extractor as build
    return build


@lru_cache(maxsize=None)
def _default_lpips_network(net_type: str, spatial: bool) -> Callable[..., Array]:
    """Cache the default-weights net per backbone: one jitted extractor whose XLA cache
    is shared across functional calls, instead of re-initialising per call."""
    feats_fn = _lpips_backbone_builder(net_type)()
    return make_lpips_net(feats_fn, lin_weights=load_lpips_heads(net_type), spatial=spatial)


def _valid_img(img: Array, normalize: bool) -> bool:
    """Input domain check (reference ``lpips.py:331-334``)."""
    value_check = bool(img.max() <= 1.0 and img.min() >= 0.0) if normalize else bool(img.min() >= -1)
    return img.ndim == 4 and img.shape[1] == 3 and value_check


def _lpips_update(img1: Array, img2: Array, net: Callable[..., Array], normalize: bool) -> Tuple[Array, int]:
    """Per-batch distances + count (reference ``lpips.py:337-346``)."""
    if not (_valid_img(img1, normalize) and _valid_img(img2, normalize)):
        raise ValueError(
            "Expected both input arguments to be normalized tensors with shape [N, 3, H, W]."
            f" Got input with shape {img1.shape} and {img2.shape} and values in range"
            f" {[img1.min(), img1.max()]} and {[img2.min(), img2.max()]} when all values are"
            f" expected to be in the {[0, 1] if normalize else [-1, 1]} range."
        )
    loss = net(img1, img2, normalize=normalize).squeeze()
    return loss, img1.shape[0]


def _lpips_compute(sum_scores: Array, total: Union[Array, int], reduction: str = "mean") -> Array:
    """Reduce accumulated scores (reference ``lpips.py:349-350``)."""
    return sum_scores / total if reduction == "mean" else sum_scores


def learned_perceptual_image_patch_similarity(
    img1: Array,
    img2: Array,
    net: Union[str, Callable[..., Array]] = "alex",
    reduction: str = "mean",
    normalize: bool = False,
    allow_random_backbone: bool = False,
) -> Array:
    """LPIPS with a string backbone (bundled heads) or an injected net (reference ``lpips.py:353-401``).

    A string ``net`` without pretrained backbone weights raises unless
    ``allow_random_backbone=True`` — see :func:`lpips_network`.
    """
    if isinstance(net, str):
        net = lpips_network(net, allow_random_backbone=allow_random_backbone)
    elif not callable(net):
        raise ValueError(
            f"Argument `net={net!r}` must be a backbone name in {tuple(_N_HEADS)} or a callable built with"
            " `make_lpips_net(feats_fn, lin_weights)`."
        )
    loss, total = _lpips_update(img1, img2, net, normalize)
    return _lpips_compute(loss.sum(), total, reduction)
